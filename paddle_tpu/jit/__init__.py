"""jit: whole-program capture and compilation.

Reference: the dygraph→static stack — `ProgramTranslator`/`StaticFunction`
(`fluid/dygraph/dygraph_to_static/program_translator.py:759,232`),
`PartialProgramLayer` running the captured program as one `run_program` op
(`partial_program.py:110`), and `paddle.jit.save/load` (`fluid/dygraph/jit.py`).

TPU-native design (SURVEY.md §7 idiom table row 1): instead of AST rewriting
into a ProgramDesc, the python function is traced with JAX abstract values —
Layer parameters are temporarily rebound to tracers, ops skip the eager tape,
and the result is a pure function ``f(params, buffers, rng, *inputs)``
compiled once per input signature by `jax.jit` and cached.  The compiled
callable is itself dispatched as ONE eager op, so `.backward()` still works
through it (the whole model becomes a single tape node — the generalization
of the reference's run_program op, which appends its backward the same way,
`partial_program.py:177`).

`TrainStep` goes further and stages forward+backward+optimizer into a single
donated XLA executable — the benchmark hot path.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core import framework
from ..core.dispatch import dispatch
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..static.input_spec import InputSpec


def _tree_arrays(x):
    return jax.tree_util.tree_map(
        lambda t: t._array if isinstance(t, Tensor) else t, x
    )


class _SwappedState:
    """Temporarily rebind Layer params/buffers to given arrays (tracers)."""

    def __init__(self, tensors: Dict[str, Tensor]):
        self.tensors = tensors
        self._saved = {}

    def __enter__(self):
        self._saved = {k: t._array for k, t in self.tensors.items()}
        return self

    def bind(self, arrays: Dict[str, Any]):
        for k, t in self.tensors.items():
            if k in arrays:
                t._array = arrays[k]

    def __exit__(self, *exc):
        for k, t in self.tensors.items():
            t._array = self._saved[k]
        return False


class StaticFunction:
    """Compiled-function cache keyed by input signature (reference
    `ProgramCache` `program_translator.py:692`)."""

    def __init__(self, function: Callable, layer: Optional[Layer] = None,
                 input_spec=None):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._compiled = {}
        functools.update_wrapper(self, function)

    @property
    def concrete_programs(self):
        return list(self._compiled.values())

    def _get_state(self) -> Tuple[Dict[str, Tensor], Dict[str, Tensor]]:
        if self._layer is None:
            return {}, {}
        return self._layer.functional_state()

    def __call__(self, *args, **kwargs):
        params, buffers = self._get_state()
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        in_arrays = [t._array for t in in_tensors]
        static_args = tuple(
            a if not isinstance(a, Tensor) else None for a in args
        )

        pnames = sorted(params)
        bnames = sorted(buffers)

        sig = (
            tuple((a.shape, str(a.dtype)) for a in in_arrays),
            static_args,
            tuple(kwargs.items()) if kwargs else (),
            bool(self._layer.training) if self._layer is not None else None,
        )
        entry = self._compiled.get(sig)
        if entry is None:
            try:
                entry = self._build(args, kwargs, params, buffers, pnames,
                                    bnames)
            except jax.errors.ConcretizationTypeError as e:
                # data-dependent Python control flow (`if tensor:` /
                # tensor-bounded loop): fall back to the AST pass that
                # lowers it onto ops.cond/while_loop (reference
                # ProgramTranslator, dygraph_to_static/
                # program_translator.py:759), then retrace
                from .dy2static import ast_transform

                transformed = ast_transform(self._function)
                if transformed is None:
                    raise
                self._function = transformed
                try:
                    entry = self._build(args, kwargs, params, buffers,
                                        pnames, bnames)
                except jax.errors.ConcretizationTypeError:
                    raise e from None
            self._compiled[sig] = entry
        jitted, buf_targets = entry

        parrs = [params[k]._array for k in pnames]
        barrs = [buffers[k]._array for k in bnames]
        rng = framework.make_rng_key(0) if framework.in_trace() else framework.default_generator.next_key()

        n_out = [None]

        def run(*flat):
            # flat = (*parrs, *in_arrays) ; barrs+rng closed over via jit args
            return jitted(flat[: len(pnames)], flat[len(pnames):], barrs, rng)

        outs_and_writes = dispatch(run, *[params[k] for k in pnames], *in_tensors)
        if not isinstance(outs_and_writes, tuple):
            outs_and_writes = (outs_and_writes,)
        # split: the last len(buf_targets) outputs are buffer writes
        nb = len(buf_targets)
        outs = outs_and_writes[: len(outs_and_writes) - nb]
        writes = outs_and_writes[len(outs_and_writes) - nb:] if nb else ()
        with framework.no_grad_guard():
            for tgt, w in zip(buf_targets, writes):
                tgt._array = w._array if isinstance(w, Tensor) else w
        if len(outs) == 1:
            return outs[0]
        return outs

    def _build(self, args, kwargs, params, buffers, pnames, bnames):
        tensor_positions = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        const_args = list(args)
        layer = self._layer
        function = self._function
        buf_tensors = [buffers[k] for k in bnames]
        buf_targets_holder: List[Tensor] = []

        def pure(parrs, in_arrays, barrs, rng):
            writes: Dict[int, Any] = {}
            call_args = list(const_args)
            for pos, arr in zip(tensor_positions, in_arrays):
                call_args[pos] = Tensor(arr)
            swap_map = {k: params[k] for k in pnames}
            swap_map.update({f"__buf__{k}": buffers[k] for k in bnames})
            with _SwappedState(swap_map) as sw:
                sw.bind({k: a for k, a in zip(pnames, parrs)})
                sw.bind({f"__buf__{k}": a for k, a in zip(bnames, barrs)})
                with framework.trace_guard(rng_key=rng, writes=writes):
                    out = function(*call_args, **kwargs)
            flat_out = out if isinstance(out, (list, tuple)) else (out,)
            out_arrays = tuple(
                o._array if isinstance(o, Tensor) else jnp.asarray(o)
                for o in flat_out
            )
            # ordered buffer writes: only for known buffer tensors
            buf_targets_holder.clear()
            write_arrays = []
            for t in buf_tensors:
                if id(t) in writes:
                    buf_targets_holder.append(t)
                    write_arrays.append(writes[id(t)])
            return out_arrays + tuple(write_arrays)

        jitted = jax.jit(pure)
        # trigger trace once to discover buffer writes (fills holder)
        parrs = [params[k]._array for k in pnames]
        barrs = [buffers[k]._array for k in bnames]
        in_arrays = [args[i]._array for i in tensor_positions]
        _ = jitted.lower(parrs, in_arrays, barrs, framework.make_rng_key(0))
        return jitted, list(buf_targets_holder)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper (reference `paddle.jit.to_static`)."""

    def wrap(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, layer=fn, input_spec=input_spec)
            fn.forward = sf
            return fn
        layer = kwargs.get("layer")
        if layer is None and hasattr(fn, "__self__") and isinstance(fn.__self__, Layer):
            layer = fn.__self__
        return StaticFunction(fn, layer=layer, input_spec=input_spec)

    if function is not None:
        return wrap(function)
    return wrap


declarative = to_static


def not_to_static(fn):
    fn._paddle_not_to_static = True
    return fn


class TrainStep:
    """Fused forward+backward+optimizer step compiled to one XLA executable
    with donated params/opt-state (the TPU replacement for the reference's
    per-op dygraph training loop)."""

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 donate: bool = True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._compiled = None
        self._step = 0
        params, buffers = model.functional_state()
        self._pnames = sorted(params)
        self._bnames = sorted(buffers)
        self._params = params
        self._buffers = buffers
        self._opt_state = None
        self._donate = donate
        self._buf_order: List[str] = []

    def _build(self):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        params, buffers = self._params, self._buffers
        pnames, bnames = self._pnames, self._bnames
        buf_order_holder = self._buf_order
        from ..optimizer.optimizer import collect_lr_mults
        lr_mults = collect_lr_mults(params)

        def pure(parr: Dict[str, Any], opt_state, barr: Dict[str, Any], lr,
                 step, rng, batch):
            def loss_of(pa):
                writes: Dict[int, Any] = {}
                swap = {k: params[k] for k in pnames}
                swap.update({f"__buf__{k}": buffers[k] for k in bnames})
                with _SwappedState(swap) as sw:
                    sw.bind(pa)
                    sw.bind({f"__buf__{k}": barr[k] for k in bnames})
                    with framework.trace_guard(rng_key=rng, writes=writes):
                        batch_t = [Tensor(b) for b in batch]
                        loss = loss_fn(model, *batch_t)
                loss_arr = loss._array if isinstance(loss, Tensor) else loss
                buf_order_holder.clear()
                wmap = {}
                for k in bnames:
                    t = buffers[k]
                    if id(t) in writes:
                        buf_order_holder.append(k)
                        wmap[k] = writes[id(t)]
                return loss_arr.astype(jnp.float32), wmap

            (loss, wmap), grads = jax.value_and_grad(loss_of, has_aux=True)(parr)
            new_params, new_opt = optimizer.apply_gradients(
                parr, grads, opt_state, lr, step, lr_mults=lr_mults
            )
            new_bufs = dict(barr)
            new_bufs.update(wmap)
            return loss, new_params, new_opt, new_bufs

        donate = (1, 2) if self._donate else ()
        return jax.jit(pure, donate_argnums=donate)

    def __call__(self, *batch) -> Tensor:
        if self._compiled is None:
            self._compiled = self._build()
        if self._opt_state is None:
            self._opt_state = self.optimizer.init_state(self._params)
        self._step += 1
        parr = {k: self._params[k]._array for k in self._pnames}
        barr = {k: self._buffers[k]._array for k in self._bnames}
        batch_arrs = [b._array if isinstance(b, Tensor) else jnp.asarray(b)
                      for b in batch]
        rng = framework.default_generator.next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        loss, new_params, new_opt, new_bufs = self._compiled(
            parr, self._opt_state, barr, lr, self._step, rng, tuple(batch_arrs)
        )
        with framework.no_grad_guard():
            for k in self._pnames:
                self._params[k]._array = new_params[k]
            for k in self._bnames:
                self._buffers[k]._array = new_bufs[k]
        self._opt_state = new_opt
        return Tensor(loss)


def train_step(model, loss_fn, optimizer, donate=True):
    return TrainStep(model, loss_fn, optimizer, donate)


# ---------------------------------------------------------------------------
# save / load — deployment format (reference `paddle.jit.save/load`,
# `fluid/dygraph/jit.py:515,851`).  The portable program format is
# jax.export's serialized StableHLO plus a numpy state dict, replacing the
# reference's ProgramDesc+params files.
# ---------------------------------------------------------------------------
def save(layer, path, input_spec=None, **config):
    import os
    import pickle

    import numpy as np

    from jax import export as jexport

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    params, buffers = layer.functional_state()
    pnames, bnames = sorted(params), sorted(buffers)

    if input_spec is None:
        raise ValueError("paddle_tpu.jit.save requires input_spec")
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            shape = [1 if d is None or d < 0 else d for d in s.shape]
            specs.append(jax.ShapeDtypeStruct(tuple(shape), s.dtype))
        elif isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(s._array.shape, s._array.dtype))

    was_training = layer.training
    layer.eval()

    def infer(parrs, barrs, *inputs):
        swap = {k: params[k] for k in pnames}
        swap.update({f"__buf__{k}": buffers[k] for k in bnames})
        with _SwappedState(swap) as sw:
            sw.bind({k: a for k, a in zip(pnames, parrs)})
            sw.bind({f"__buf__{k}": a for k, a in zip(bnames, barrs)})
            with framework.trace_guard(rng_key=framework.make_rng_key(0), writes={}):
                out = layer(*[Tensor(i) for i in inputs])
        outs = out if isinstance(out, (list, tuple)) else (out,)
        return tuple(o._array for o in outs)

    parr_specs = [jax.ShapeDtypeStruct(params[k]._array.shape, params[k]._array.dtype) for k in pnames]
    barr_specs = [jax.ShapeDtypeStruct(buffers[k]._array.shape, buffers[k]._array.dtype) for k in bnames]
    exported = jexport.export(jax.jit(infer))(parr_specs, barr_specs, *specs)
    blob = exported.serialize()

    state = {k: np.asarray(params[k]._array) for k in pnames}
    bufs = {k: np.asarray(buffers[k]._array) for k in bnames}
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"params": state, "buffers": bufs,
                     "pnames": pnames, "bnames": bnames}, f)
    if was_training:
        layer.train()


class TranslatedLayer(Layer):
    """Deserialized deployable module (reference TranslatedLayer,
    `fluid/dygraph/io.py`)."""

    def __init__(self, exported, params, buffers, pnames, bnames):
        super().__init__()
        self._exported = exported
        self._pnames = pnames
        self._bnames = bnames
        from ..nn.layer.layers import Parameter

        for k in pnames:
            self.add_parameter(k.replace(".", "__"), Parameter(params[k]))
        for k in bnames:
            self.register_buffer(k.replace(".", "__"), Tensor(buffers[k]))
        self._param_map = {k: self._parameters[k.replace(".", "__")] for k in pnames}
        self._buf_map = {k: self._buffers[k.replace(".", "__")] for k in bnames}

    def forward(self, *inputs):
        parrs = [self._param_map[k]._array for k in self._pnames]
        barrs = [self._buf_map[k]._array for k in self._bnames]
        in_arrs = [i._array if isinstance(i, Tensor) else jnp.asarray(i)
                   for i in inputs]
        outs = self._exported.call(parrs, barrs, *in_arrs)
        outs = tuple(Tensor(o) for o in outs)
        return outs[0] if len(outs) == 1 else outs


def load(path, **config):
    import pickle

    from jax import export as jexport

    with open(path + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(bytearray(f.read()))
    with open(path + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    return TranslatedLayer(exported, meta["params"], meta["buffers"],
                           meta["pnames"], meta["bnames"])


class TracedLayer:
    """reference `fluid/dygraph/jit.py:49` TracedLayer (trace+run)."""

    def __init__(self, static_fn, layer):
        self._fn = static_fn
        self._layer = layer

    @staticmethod
    def trace(layer, inputs):
        sf = StaticFunction(layer.forward, layer=layer)
        out = sf(*inputs)
        return out, TracedLayer(sf, layer)

    def __call__(self, *inputs):
        return self._fn(*inputs)
