"""Dygraph-to-static AST fallback for data-dependent Python control flow.

Reference: the ProgramTranslator's transformer stack
(`fluid/dygraph/dygraph_to_static/program_translator.py:759`, ~15 AST
transformers).  The TPU build's `jit.to_static` is trace-based (SURVEY §7
sanctioned): Python control flow on *concrete* values folds into the
trace for free.  What tracing cannot do is branch/loop on a TRACED
tensor — `if tensor:` raises a jax concretization error.  This module is
the fallback for exactly that case: a minimal AST pass that rewrites

* ``if <tensor>: ... else: ...``     -> ``ops.cond`` over branch closures
* ``while <tensor-cond>: ...``       -> ``ops.while_loop`` over loop vars
* ``for i in range(<tensor-n>): ...``-> counter ``while`` (then as above)

`StaticFunction` retries a failed trace through `maybe_transform` — so
the AST pass only ever runs for functions that actually need it, and
programs that trace cleanly keep the pure-trace path.

Scope (documented constraints, mirroring the XLA requirements):
branches/loops containing ``return``/``break``/``continue`` or
``try``/``with`` are left unrewritten; loop-carried variables must be
defined before the loop and keep loop-invariant shapes/dtypes.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set


class _Undef:
    """Sentinel for names not yet bound before a rewritten `if` (they
    must then be assigned by the taken branch before any later read)."""

    __slots__ = ()

    def __repr__(self):
        return "<undefined>"


_PT_UNDEF = _Undef()


def _pt_if(pred, true_fn, false_fn, operands):
    from ..ops import control_flow as cf

    return cf.cond(pred, lambda: true_fn(*operands),
                   lambda: false_fn(*operands))


def _pt_while(cond_fn, body_fn, init):
    from ..ops import control_flow as cf

    out = cf.while_loop(cond_fn, body_fn, list(init))
    return tuple(out)


class _Assigned(ast.NodeVisitor):
    """Names bound by statements (assign targets, aug-assign, for
    targets) — NOT descending into nested function/class defs."""

    def __init__(self):
        self.names: Set[str] = set()

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    def visit_AsyncFunctionDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned_names(stmts: List[ast.stmt]) -> Set[str]:
    v = _Assigned()
    for s in stmts:
        v.visit(s)
    return v.names


def _loaded_names(nodes) -> Set[str]:
    out: Set[str] = set()
    for n in nodes if isinstance(nodes, list) else [nodes]:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                out.add(sub.id)
    return out


def _has_flow_escape(stmts: List[ast.stmt]) -> bool:
    """Return/break/continue/try/with anywhere in the (non-nested-def)
    statement tree — constructs the rewrite cannot represent."""
    for s in stmts:
        for sub in ast.walk(s):
            if isinstance(sub, (ast.Return, ast.Break, ast.Continue,
                                ast.Try, ast.With, ast.Yield,
                                ast.YieldFrom)):
                return True
    return False


class _ControlFlowRewriter(ast.NodeTransformer):
    """Rewrites If/While/For-range statements inside a function body.

    Generated branch/body closures take the mutated names as PARAMETERS
    (current values snapshotted at the call): under a traced cond both
    branches execute, so writes from one branch must not leak into the
    other's trace; the merged values come back through the helper's
    return tuple."""

    def __init__(self):
        super().__init__()
        self._uid = 0
        # statements following the node being rewritten, per nesting
        # level — used to decide which while-assigned names must be
        # carried out of the loop
        self._after_stack: List[List[ast.stmt]] = []

    def _fresh(self, tag):
        self._uid += 1
        return f"_pt_{tag}_{self._uid}"

    @staticmethod
    def _undef_guard(name):
        """try: name / except NameError: name = _PT_UNDEF — lets the
        operand tuple evaluate when the name is first bound inside the
        rewritten block (matching Python, a later real read of an
        undefined result still fails, just less precisely)."""
        return ast.Try(
            body=[ast.Expr(value=ast.Name(id=name, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=name, ctx=ast.Store())],
                    value=ast.Name(id="_PT_UNDEF", ctx=ast.Load()))])],
            orelse=[], finalbody=[])

    def _rewrite_body(self, stmts, after):
        self._after_stack.append(after)
        out = []
        for i, s in enumerate(stmts):
            self._after_stack[-1] = stmts[i + 1:] + after
            r = self.visit(s)
            out.extend(r if isinstance(r, list) else [r])
        self._after_stack.pop()
        return out

    # -- function roots ------------------------------------------------------
    def visit_FunctionDef(self, node):
        node.body = self._rewrite_body(node.body, [])
        return node

    # -- if on a (possibly) traced tensor ------------------------------------
    def visit_If(self, node):
        after = list(self._after_stack[-1]) if self._after_stack else []
        body = self._rewrite_body(node.body, after)
        orelse = self._rewrite_body(node.orelse, after)
        if _has_flow_escape(body) or _has_flow_escape(orelse):
            node.body, node.orelse = body, orelse
            return node
        # carry only the mutated names that are READ after the if (the
        # test already ran); branch-local temporaries stay local to their
        # branch closure — carrying them would hand the other branch a
        # _PT_UNDEF it cannot return through lax.cond
        assigned = _assigned_names(body) | _assigned_names(orelse)
        names = sorted(assigned & _loaded_names(after))
        tf_name, ff_name = self._fresh("true"), self._fresh("false")

        # Branch closures take the CURRENT values of every mutated name
        # as parameters (no nonlocal: under a traced cond both branches
        # run, and writes from the first must not leak into the second's
        # trace); the merged values come back via the helper's result.
        def branch(fname, stmts):
            inner: List[ast.stmt] = list(stmts)
            inner.append(ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
                ctx=ast.Load())))
            return ast.FunctionDef(
                name=fname,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=n) for n in names],
                    kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=inner, decorator_list=[])

        # names first bound inside the branches need a placeholder so the
        # operand tuple evaluates: try: n \n except NameError: n = _PT_UNDEF
        guards = [self._undef_guard(n) for n in names]
        call = ast.Call(
            func=ast.Name(id="_pt_if", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tf_name, ctx=ast.Load()),
                  ast.Name(id=ff_name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                  for n in names], ctx=ast.Load())],
            keywords=[])
        if names:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        new = [branch(tf_name, body or [ast.Pass()]),
               branch(ff_name, orelse or [ast.Pass()])] + guards + [assign]
        for n in new:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return new

    # -- while on a traced condition -----------------------------------------
    def visit_While(self, node):
        after = list(self._after_stack[-1]) if self._after_stack else []
        body = self._rewrite_body(node.body, after)
        if _has_flow_escape(body) or node.orelse:
            node.body = body
            return node
        assigned = _assigned_names(body)
        needed = _loaded_names([node.test]) | _loaded_names(after) | \
            _loaded_names(body)
        names = sorted(assigned & needed)
        if not names:
            node.body = body
            return node
        cond_name, body_name = self._fresh("cond"), self._fresh("body")
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=body_name, args=args,
            body=list(body) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
                ctx=ast.Load()))],
            decorator_list=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="_pt_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_name, ctx=ast.Load()),
                      ast.Name(id=body_name, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                      for n in names], ctx=ast.Load())],
                keywords=[]))
        guards = [self._undef_guard(n) for n in names]
        new = [cond_fn, body_fn] + guards + [assign]
        for n in new:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return new

    # -- for i in range(n) with a possibly-traced n --------------------------
    def visit_For(self, node):
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and len(node.iter.args) == 1
                and isinstance(node.target, ast.Name)
                and not node.orelse
                and not _has_flow_escape(node.body)):
            # leave untransformable loops alone (break/continue would skip
            # a desugared counter bump and hang) — but still rewrite
            # control flow nested inside the body
            node.body = self._rewrite_body(
                node.body,
                list(self._after_stack[-1]) if self._after_stack else [])
            return node
        # for i in range(n): body
        #   -> _pt_i = 0; while _pt_i < n: i = _pt_i; body; _pt_i += 1
        # The hidden counter keeps Python's post-loop semantics for the
        # user variable: i ends at n-1, and stays unbound when n == 0.
        i_name = node.target.id
        ctr = self._fresh("iter")
        init = ast.Assign(
            targets=[ast.Name(id=ctr, ctx=ast.Store())],
            value=ast.Constant(value=0))
        head = ast.Assign(
            targets=[ast.Name(id=i_name, ctx=ast.Store())],
            value=ast.Name(id=ctr, ctx=ast.Load()))
        bump = ast.AugAssign(
            target=ast.Name(id=ctr, ctx=ast.Store()),
            op=ast.Add(), value=ast.Constant(value=1))
        loop = ast.While(
            test=ast.Compare(left=ast.Name(id=ctr, ctx=ast.Load()),
                             ops=[ast.Lt()],
                             comparators=[node.iter.args[0]]),
            body=[head] + list(node.body) + [bump], orelse=[])
        for n in (init, loop, head, bump):
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        rewritten = self.visit_While(loop)
        return [init] + (rewritten if isinstance(rewritten, list)
                         else [rewritten])


def ast_transform(fn: Callable) -> Optional[Callable]:
    """Rewrite ``fn``'s tensor-dependent control flow; None when the
    source is unavailable (builtins, lambdas in REPLs) or nothing was
    rewritten."""
    bound_self = getattr(fn, "__self__", None)
    raw = fn.__func__ if bound_self is not None else fn
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []
    rewriter = _ControlFlowRewriter()
    rewriter.visit(fdef)
    if rewriter._uid == 0:
        return None  # nothing to rewrite
    ast.fix_missing_locations(tree)

    # evaluate in the original globals plus closure cells + helpers
    glb = dict(raw.__globals__)
    glb["_pt_if"] = _pt_if
    glb["_pt_while"] = _pt_while
    glb["_PT_UNDEF"] = _PT_UNDEF
    if raw.__closure__:
        for name, cell in zip(raw.__code__.co_freevars, raw.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    code = compile(tree, filename=f"<dy2static {raw.__qualname__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, glb, ns)  # noqa: S102 - compiling the user's own source
    new_fn = ns[fdef.name]
    if raw.__defaults__:
        new_fn.__defaults__ = raw.__defaults__
    functools.update_wrapper(new_fn, raw)
    if bound_self is not None:
        return new_fn.__get__(bound_self, type(bound_self))
    return new_fn
