"""Dygraph-to-static AST fallback for data-dependent Python control flow.

Reference: the ProgramTranslator's transformer stack
(`fluid/dygraph/dygraph_to_static/program_translator.py:759`, ~15 AST
transformers).  The TPU build's `jit.to_static` is trace-based (SURVEY §7
sanctioned): Python control flow on *concrete* values folds into the
trace for free.  What tracing cannot do is branch/loop on a TRACED
tensor — `if tensor:` raises a jax concretization error.  This module is
the fallback for exactly that case: a minimal AST pass that rewrites

* ``if <tensor>: ... else: ...``     -> ``ops.cond`` over branch closures
* ``while <tensor-cond>: ...``       -> ``ops.while_loop`` over loop vars
* ``for i in range(<tensor-n>): ...``-> counter ``while`` (then as above)

`StaticFunction` retries a failed trace through `maybe_transform` — so
the AST pass only ever runs for functions that actually need it, and
programs that trace cleanly keep the pure-trace path.

Flow-escape statements (round 4, mirroring the reference's
`break_continue_transformer.py` / `return_transformer.py`):
``return``/``break``/``continue`` inside rewritten blocks desugar to
BOOLEAN GUARD CARRIES before the control-flow rewrite —
``return e`` -> ``_pt_ret_val = e; _pt_ret_flag = True`` with every
subsequent statement guarded by ``if _pt_not(_pt_ret_flag)``, loop tests
conjoined with the negated flags, ``break``/``continue`` -> per-loop
flags with the same guarding (the for-range counter bump stays
unguarded so ``continue`` still advances).

Remaining constraints (XLA requirements): ``try``/``with``/``yield``
inside rewritten blocks are left unrewritten; every return path through
tensor-dependent control flow must produce the same pytree structure;
loop-carried variables must be defined before the loop and keep
loop-invariant shapes/dtypes; reverse-mode gradients do NOT flow through
a rewritten ``while`` (lax.while_loop is not reverse-differentiable —
use a bounded ``for i in range(n)`` when the loop must be trained
through).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Callable, List, Optional, Set


class _Undef:
    """Sentinel for names not yet bound before a rewritten `if` (they
    must then be assigned by the taken branch before any later read)."""

    __slots__ = ()

    def __repr__(self):
        return "<undefined>"


_PT_UNDEF = _Undef()

# empty-pytree registration: _PT_UNDEF survives jax.eval_shape probing
# and lax.cond structure checks as a zero-leaf container
import jax as _jax  # noqa: E402

_jax.tree_util.register_pytree_node(
    _Undef, lambda u: ((), None), lambda aux, ch: _PT_UNDEF)


def _is_hole(v):
    return v is None or isinstance(v, _Undef) or isinstance(v, bool)


def _pt_if(pred, true_fn, false_fn, operands):
    """cond over the branch closures.  Slots a branch leaves undefined
    (None/_PT_UNDEF — e.g. `_pt_ret_val` on the path that doesn't
    return) are PROMOTED to zeros of the other branch's shape/dtype so
    lax.cond sees matching pytrees; the guard flags guarantee a promoted
    placeholder is never read."""
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..ops import control_flow as cf

    # structural holes can only enter through hole OPERANDS (a branch
    # that doesn't bind a name returns the incoming placeholder) — skip
    # the double abstract trace entirely in the common no-hole case
    if not any(v is None or isinstance(v, _Undef) for v in operands):
        return cf.cond(pred, lambda: true_fn(*operands),
                       lambda: false_fn(*operands))

    def spec_of(fn):
        def probe(ops):
            out = fn(*ops)
            if not isinstance(out, tuple):
                return out
            return tuple(v._array if isinstance(v, Tensor) else v
                         for v in out)

        try:
            probe_ops = tuple(v._array if isinstance(v, Tensor) else v
                              for v in operands)
            return jax.eval_shape(probe, probe_ops)
        except Exception:
            return None

    s_t, s_f = spec_of(true_fn), spec_of(false_fn)
    if (isinstance(s_t, tuple) and isinstance(s_f, tuple)
            and len(s_t) == len(s_f)):
        promos = []
        for a, b in zip(s_t, s_f):
            a_arr, b_arr = hasattr(a, "shape"), hasattr(b, "shape")
            promos.append((a if a_arr else b) if a_arr != b_arr
                          else None)
        if any(p is not None for p in promos):
            def fill(out):
                vals = out if isinstance(out, tuple) else (out,)
                return tuple(
                    jnp.zeros(p.shape, p.dtype)
                    if p is not None and _is_hole(
                        v._array if isinstance(v, Tensor) else v)
                    else v
                    for v, p in zip(vals, promos))

            return cf.cond(pred, lambda: fill(true_fn(*operands)),
                           lambda: fill(false_fn(*operands)))
    return cf.cond(pred, lambda: true_fn(*operands),
                   lambda: false_fn(*operands))


def _pt_while(cond_fn, body_fn, init):
    """while_loop over the carried names.  Carry slots whose initial
    value is a hole (None/_PT_UNDEF — e.g. `_pt_ret_val` before any
    return ran) are promoted to zeros of the body's output spec; slots
    that STAY holes (per eval_shape) are excluded from the lax carry and
    passed through as constants."""
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..ops import control_flow as cf

    init = list(init)

    def uw(v):
        return v._array if isinstance(v, Tensor) else v

    if not any(v is None or isinstance(v, _Undef) for v in init):
        out = cf.while_loop(cond_fn, body_fn, init)
        return tuple(out)

    try:
        spec = jax.eval_shape(
            lambda ops: tuple(uw(v) for v in body_fn(*ops)),
            tuple(uw(v) for v in init))
    except Exception:
        spec = None
    holes = set()
    if isinstance(spec, tuple) and len(spec) == len(init):
        for i, (iv, sp) in enumerate(zip(init, spec)):
            iv_hole = iv is None or isinstance(iv, _Undef)
            if iv_hole and hasattr(sp, "shape"):
                init[i] = jnp.zeros(sp.shape, sp.dtype)
            elif iv_hole:
                holes.add(i)
    if holes:
        const = {i: init[i] for i in holes}
        carried = [i for i in range(len(init)) if i not in holes]

        def expand(args):
            full, it = [], iter(args)
            for i in range(len(init)):
                full.append(const[i] if i in holes else next(it))
            return full

        out = cf.while_loop(
            lambda *a: cond_fn(*expand(a)),
            lambda *a: tuple(body_fn(*expand(a))[i] for i in carried),
            [init[i] for i in carried])
        return tuple(expand(out))
    out = cf.while_loop(cond_fn, body_fn, init)
    return tuple(out)


def _pt_not(x):
    """Logical not that works on python bools AND traced tensors (the
    guard flags start as python False and become traced after the first
    rewritten branch writes them)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if isinstance(x, Tensor):
        x = x._array
    if isinstance(x, bool):
        return not x
    return jnp.logical_not(x)


def _pt_and(a, b):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    a = a._array if isinstance(a, Tensor) else a
    b = b._array if isinstance(b, Tensor) else b
    if isinstance(a, bool) and isinstance(b, bool):
        return a and b
    return jnp.logical_and(a, b)


class _Assigned(ast.NodeVisitor):
    """Names bound by statements (assign targets, aug-assign, for
    targets) — NOT descending into nested function/class defs."""

    def __init__(self):
        self.names: Set[str] = set()
        self.funcs: Set[str] = set()

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # a def binds a FUNCTION object — never carryable through
        # lax.cond/while (and the rewriter regenerates its closures
        # inside each branch/body anyway)
        self.funcs.add(node.name)

    def visit_AsyncFunctionDef(self, node):
        self.funcs.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned_names(stmts: List[ast.stmt]) -> Set[str]:
    v = _Assigned()
    for s in stmts:
        v.visit(s)
    return v.names - v.funcs


def _loaded_names(nodes) -> Set[str]:
    out: Set[str] = set()
    for n in nodes if isinstance(nodes, list) else [nodes]:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                out.add(sub.id)
    return out


def _has_flow_escape(stmts: List[ast.stmt]) -> bool:
    """try/with/yield anywhere in the (non-nested-def) statement tree —
    constructs the rewrite cannot represent.  return/break/continue are
    DESUGARED to guard flags before this check runs (round 4); a
    leftover one (e.g. inside try) still blocks the rewrite.  The
    undef-guard Try statements the desugar itself emits are exempt."""
    def walk(node):
        for child in ast.iter_child_nodes(node):
            if getattr(child, "_pt_generated", False):
                continue
            if isinstance(child, (ast.Return, ast.Break, ast.Continue,
                                  ast.Try, ast.With, ast.Yield,
                                  ast.YieldFrom)):
                return True
            # a return/break inside a nested def does NOT escape the
            # enclosing block (and generated branch closures end in one)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if walk(child):
                return True
        return False

    for s in stmts:
        if getattr(s, "_pt_generated", False):
            continue
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested defs (incl. generated closures) don't escape
        if isinstance(s, (ast.Return, ast.Break, ast.Continue, ast.Try,
                          ast.With)):
            return True
        if walk(s):
            return True
    return False




# ---------------------------------------------------------------------------
# flow-escape desugaring (round 4) — the reference's
# `dygraph_to_static/return_transformer.py` and
# `break_continue_transformer.py` re-thought as boolean guard carries:
# the flags travel through lax.cond/while carries like any other value.
# ---------------------------------------------------------------------------
def _name(n, ctx=None):
    return ast.Name(id=n, ctx=ctx or ast.Load())


def _assign(target, value):
    a = ast.Assign(targets=[_name(target, ast.Store())], value=value)
    a._pt_flagset = True
    return a


def _call(fn, *args):
    return ast.Call(func=_name(fn), args=list(args), keywords=[])


def _sets_flags(stmt, flags) -> bool:
    """Does stmt (not descending into nested defs) assign any flag?"""
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Name) and t.id in flags:
                    return True
    return False


def _guard_after(stmts, flags, guard_expr_fn):
    """Wrap every statement FOLLOWING a flag-setting one in
    ``if <not flags>:`` so a taken return/break skips the rest of the
    block — recursively, preserving relative order."""
    out: List[ast.stmt] = []
    for i, s in enumerate(stmts):
        out.append(s)
        if _sets_flags(s, flags) and i + 1 < len(stmts):
            rest = _guard_after(stmts[i + 1:], flags, guard_expr_fn)
            g = ast.If(test=guard_expr_fn(), body=rest, orelse=[])
            ast.copy_location(g, s)
            out.append(g)
            break
    return out


class _ReturnDesugar:
    """``return e`` (below the top level) ->
    ``_pt_ret_val = e; _pt_ret_flag = True`` + guards + loop-test
    conjuncts + a single trailing ``return _pt_ret_val``."""

    FLAG = "_pt_ret_flag"
    VAL = "_pt_ret_val"

    def run(self, fdef) -> bool:
        if not self._has_nested_return(fdef.body):
            return False
        body = self._rewrite(fdef.body)
        body = _guard_after(body, {self.FLAG}, self._guard)
        init = [
            _assign(self.FLAG, ast.Constant(value=False)),
            _assign(self.VAL, ast.Constant(value=None)),
        ]
        tail = [ast.Return(value=_name(self.VAL))]
        for n in init + tail:
            ast.copy_location(n, fdef.body[0])
        fdef.body = init + body + tail
        ast.fix_missing_locations(fdef)
        return True

    def _guard(self):
        return _call("_pt_not", _name(self.FLAG))

    @staticmethod
    def _has_nested_return(stmts) -> bool:
        def walk(ss, top):
            for s in ss:
                if isinstance(s, ast.Return) and not top:
                    return True
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                for field in ("body", "orelse", "finalbody"):
                    if walk(getattr(s, field, []) or [], False):
                        return True
            return False

        return walk(stmts, True)

    def _rewrite(self, stmts):
        out = []
        for s in stmts:
            if isinstance(s, ast.Return):
                val = s.value if s.value is not None else \
                    ast.Constant(value=None)
                a1 = _assign(self.VAL, val)
                a2 = _assign(self.FLAG, ast.Constant(value=True))
                for a in (a1, a2):
                    ast.copy_location(a, s)
                out += [a1, a2]
                continue
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(s)
                continue
            if isinstance(s, ast.If):
                s.body = _guard_after(self._rewrite(s.body),
                                      {self.FLAG}, self._guard)
                s.orelse = _guard_after(self._rewrite(s.orelse),
                                        {self.FLAG}, self._guard)
            elif isinstance(s, (ast.While, ast.For)):
                had = self._subtree_returns(s)
                s.body = _guard_after(self._rewrite(s.body),
                                      {self.FLAG}, self._guard)
                if had:
                    if isinstance(s, ast.While):
                        s.test = _call("_pt_and", s.test, self._guard())
                    else:
                        # range-form fors get the while-test conjunct in
                        # visit_For; CONCRETE fors (e.g. over layers)
                        # keep iterating in python, so each iteration's
                        # whole body must be skipped once returned
                        s._pt_ret_inside = True
                        g = ast.If(test=self._guard(), body=s.body,
                                   orelse=[])
                        ast.copy_location(g, s)
                        s.body = [g]
            out.append(s)
        return out

    @staticmethod
    def _subtree_returns(node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(sub, ast.Return):
                return True
        return False


class _BreakContinueDesugar:
    """Per-loop ``break``/``continue`` -> flags + guards.  Runs
    inner-loops-first so each break binds to ITS loop."""

    def __init__(self):
        self._n = 0
        self.rewrote = False

    def _fresh(self, tag):
        self._n += 1
        self.rewrote = True
        return f"_pt_{tag}_{self._n}"

    def run(self, fdef):
        fdef.body = self._walk_block(fdef.body)
        ast.fix_missing_locations(fdef)

    def _walk_block(self, stmts):
        out = []
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(s)
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(s, field, None)
                if sub:
                    setattr(s, field, self._walk_block(sub))
            if isinstance(s, (ast.While, ast.For)):
                self._desugar_loop(s)
                # flags must exist before the loop: they ride the while
                # carry (assigned in body, read in test/guards)
                for f in getattr(s, "_pt_flag_inits", []):
                    init = _assign(f, ast.Constant(value=False))
                    ast.copy_location(init, s)
                    out.append(init)
            out.append(s)
        return out

    @staticmethod
    def _collect(stmts, kinds):
        """break/continue at THIS loop level (descend into ifs, not into
        nested loops/defs)."""
        found = []

        def walk(ss):
            for s in ss:
                if isinstance(s, kinds):
                    found.append(s)
                # Try/With block the control-flow rewrite, so a
                # break/continue inside them must stay a real statement
                # (leaving it makes _has_flow_escape refuse cleanly)
                if isinstance(s, (ast.While, ast.For, ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Try,
                                  ast.With)):
                    continue
                for field in ("body", "orelse", "finalbody"):
                    walk(getattr(s, field, []) or [])

        walk(stmts)
        return found

    @staticmethod
    def _is_range_for(loop) -> bool:
        return (isinstance(loop, ast.For)
                and isinstance(loop.iter, ast.Call)
                and isinstance(loop.iter.func, ast.Name)
                and loop.iter.func.id == "range"
                and len(loop.iter.args) == 1
                and isinstance(loop.target, ast.Name))

    def _desugar_loop(self, loop):
        brks = self._collect(loop.body, ast.Break)
        conts = self._collect(loop.body, ast.Continue)
        if not brks and not conts:
            return
        # python skips a loop's else on break — removing the break would
        # make it always run; leave the statements so the rewrite refuses
        if loop.orelse:
            return
        # break needs a test that consults the flag: only While and
        # single-arg-range For (desugared to While) have one.  A break
        # in a concrete for (e.g. over layers) has nothing to stop the
        # iteration — leave it so _has_flow_escape blocks the rewrite.
        if brks and not (isinstance(loop, ast.While)
                         or self._is_range_for(loop)):
            return
        flags = []
        brk = cont = None
        if brks:
            brk = self._fresh("brk")
            flags.append(brk)
        if conts:
            cont = self._fresh("cont")
            flags.append(cont)

        def replace(ss):
            out = []
            for s in ss:
                if isinstance(s, ast.Break) and brk:
                    a = _assign(brk, ast.Constant(value=True))
                    ast.copy_location(a, s)
                    out.append(a)
                elif isinstance(s, ast.Continue) and cont:
                    a = _assign(cont, ast.Constant(value=True))
                    ast.copy_location(a, s)
                    out.append(a)
                else:
                    if not isinstance(s, (ast.While, ast.For,
                                          ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Try, ast.With)):
                        for field in ("body", "orelse", "finalbody"):
                            if getattr(s, field, None):
                                setattr(s, field,
                                        replace(getattr(s, field)))
                    out.append(s)
            return out

        def guard():
            e = _call("_pt_not", _name(flags[0]))
            if len(flags) == 2:
                e = _call("_pt_and",
                          _call("_pt_not", _name(flags[0])),
                          _call("_pt_not", _name(flags[1])))
            return e

        body = _guard_after(replace(loop.body), set(flags), guard)
        head = []
        if cont:
            head.append(_assign(cont, ast.Constant(value=False)))
        loop.body = head + body
        if brk:
            if isinstance(loop, ast.While):
                loop.test = _call("_pt_and", loop.test,
                                  _call("_pt_not", _name(brk)))
            else:
                loop._pt_brk_flag = brk
        # every flag must exist before the loop runs (they ride the
        # while carry)
        loop._pt_flag_inits = getattr(loop, "_pt_flag_inits", []) + flags
        ast.fix_missing_locations(loop)


class _ControlFlowRewriter(ast.NodeTransformer):
    """Rewrites If/While/For-range statements inside a function body.

    Generated branch/body closures take the mutated names as PARAMETERS
    (current values snapshotted at the call): under a traced cond both
    branches execute, so writes from one branch must not leak into the
    other's trace; the merged values come back through the helper's
    return tuple."""

    def __init__(self):
        super().__init__()
        self._uid = 0
        # statements following the node being rewritten, per nesting
        # level — used to decide which while-assigned names must be
        # carried out of the loop
        self._after_stack: List[List[ast.stmt]] = []

    def _fresh(self, tag):
        self._uid += 1
        return f"_pt_{tag}_{self._uid}"

    @staticmethod
    def _undef_guard(name):
        """try: name / except NameError: name = _PT_UNDEF — lets the
        operand tuple evaluate when the name is first bound inside the
        rewritten block (matching Python, a later real read of an
        undefined result still fails, just less precisely)."""
        t = ast.Try(
            body=[ast.Expr(value=ast.Name(id=name, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=name, ctx=ast.Store())],
                    value=ast.Name(id="_PT_UNDEF", ctx=ast.Load()))])],
            orelse=[], finalbody=[])
        t._pt_generated = True
        return t

    def _rewrite_body(self, stmts, after):
        self._after_stack.append(after)
        out = []
        for i, s in enumerate(stmts):
            self._after_stack[-1] = stmts[i + 1:] + after
            r = self.visit(s)
            out.extend(r if isinstance(r, list) else [r])
        self._after_stack.pop()
        return out

    # -- function roots ------------------------------------------------------
    def visit_FunctionDef(self, node):
        node.body = self._rewrite_body(node.body, [])
        return node

    # -- if on a (possibly) traced tensor ------------------------------------
    def visit_If(self, node):
        after = list(self._after_stack[-1]) if self._after_stack else []
        body = self._rewrite_body(node.body, after)
        orelse = self._rewrite_body(node.orelse, after)
        if _has_flow_escape(body) or _has_flow_escape(orelse):
            node.body, node.orelse = body, orelse
            return node
        # carry mutated names read after the if OR read inside a branch
        # (read-before-write of the outer value would otherwise become
        # an UnboundLocalError in the closure).  Pure branch-local temps
        # ride along as holes: _pt_if promotes a slot the other branch
        # leaves undefined (_PT_UNDEF -> zeros), and the guard flags
        # keep promoted placeholders unread.
        assigned = _assigned_names(body) | _assigned_names(orelse)
        names = sorted(assigned & (_loaded_names(after)
                                   | _loaded_names(body)
                                   | _loaded_names(orelse)))
        tf_name, ff_name = self._fresh("true"), self._fresh("false")

        # Branch closures take the CURRENT values of every mutated name
        # as parameters (no nonlocal: under a traced cond both branches
        # run, and writes from the first must not leak into the second's
        # trace); the merged values come back via the helper's result.
        def branch(fname, stmts):
            inner: List[ast.stmt] = list(stmts)
            inner.append(ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
                ctx=ast.Load())))
            return ast.FunctionDef(
                name=fname,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=n) for n in names],
                    kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=inner, decorator_list=[])

        # names first bound inside the branches need a placeholder so the
        # operand tuple evaluates: try: n \n except NameError: n = _PT_UNDEF
        guards = [self._undef_guard(n) for n in names]
        call = ast.Call(
            func=ast.Name(id="_pt_if", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tf_name, ctx=ast.Load()),
                  ast.Name(id=ff_name, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                  for n in names], ctx=ast.Load())],
            keywords=[])
        if names:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        new = [branch(tf_name, body or [ast.Pass()]),
               branch(ff_name, orelse or [ast.Pass()])] + guards + [assign]
        for n in new:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return new

    # -- while on a traced condition -----------------------------------------
    def visit_While(self, node):
        after = list(self._after_stack[-1]) if self._after_stack else []
        # inside a loop body, "read later" includes the NEXT iteration:
        # the loop test and the body itself load names the current
        # iteration's rewritten ifs must carry out
        test_probe = ast.Expr(value=node.test)
        loop_after = [test_probe] + list(node.body) + after
        body = self._rewrite_body(node.body, loop_after)
        if _has_flow_escape(body) or node.orelse:
            node.body = body
            return node
        assigned = _assigned_names(body)
        needed = _loaded_names([node.test]) | _loaded_names(after) | \
            _loaded_names(body)
        names = sorted(assigned & needed)
        if not names:
            node.body = body
            return node
        cond_name, body_name = self._fresh("cond"), self._fresh("body")
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=body_name, args=args,
            body=list(body) + [ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
                ctx=ast.Load()))],
            decorator_list=[])
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="_pt_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_name, ctx=ast.Load()),
                      ast.Name(id=body_name, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                      for n in names], ctx=ast.Load())],
                keywords=[]))
        guards = [self._undef_guard(n) for n in names]
        new = [cond_fn, body_fn] + guards + [assign]
        for n in new:
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        return new

    # -- for i in range(n) with a possibly-traced n --------------------------
    def visit_For(self, node):
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and len(node.iter.args) == 1
                and isinstance(node.target, ast.Name)
                and not node.orelse
                and not _has_flow_escape(node.body)):
            # leave untransformable loops alone (break/continue would skip
            # a desugared counter bump and hang) — but still rewrite
            # control flow nested inside the body
            node.body = self._rewrite_body(
                node.body,
                list(self._after_stack[-1]) if self._after_stack else [])
            return node
        # for i in range(n): body
        #   -> _pt_i = 0; while _pt_i < n: i = _pt_i; body; _pt_i += 1
        # The hidden counter keeps Python's post-loop semantics for the
        # user variable: i ends at n-1, and stays unbound when n == 0.
        i_name = node.target.id
        ctr = self._fresh("iter")
        init = ast.Assign(
            targets=[ast.Name(id=ctr, ctx=ast.Store())],
            value=ast.Constant(value=0))
        head = ast.Assign(
            targets=[ast.Name(id=i_name, ctx=ast.Store())],
            value=ast.Name(id=ctr, ctx=ast.Load()))
        bump = ast.AugAssign(
            target=ast.Name(id=ctr, ctx=ast.Store()),
            op=ast.Add(), value=ast.Constant(value=1))
        test = ast.Compare(left=ast.Name(id=ctr, ctx=ast.Load()),
                           ops=[ast.Lt()],
                           comparators=[node.iter.args[0]])
        brk_flag = getattr(node, "_pt_brk_flag", None)
        if brk_flag:  # break inside: stop as soon as the flag is set
            test = _call("_pt_and", test,
                         _call("_pt_not", _name(brk_flag)))
        if getattr(node, "_pt_ret_inside", False):
            test = _call("_pt_and", test,
                         _call("_pt_not", _name(_ReturnDesugar.FLAG)))
        loop = ast.While(
            test=test,
            body=[head] + list(node.body) + [bump], orelse=[])
        for n in (init, loop, head, bump):
            ast.copy_location(n, node)
            ast.fix_missing_locations(n)
        rewritten = self.visit_While(loop)
        return [init] + (rewritten if isinstance(rewritten, list)
                         else [rewritten])


def ast_transform(fn: Callable) -> Optional[Callable]:
    """Rewrite ``fn``'s tensor-dependent control flow; None when the
    source is unavailable (builtins, lambdas in REPLs) or nothing was
    rewritten."""
    bound_self = getattr(fn, "__self__", None)
    raw = fn.__func__ if bound_self is not None else fn
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []
    ret_pass = _ReturnDesugar()
    ret_rewrote = ret_pass.run(fdef)
    bc_pass = _BreakContinueDesugar()
    bc_pass.run(fdef)
    rewriter = _ControlFlowRewriter()
    rewriter.visit(fdef)
    if rewriter._uid == 0 and not ret_rewrote and not bc_pass.rewrote:
        return None  # nothing to rewrite
    ast.fix_missing_locations(tree)

    # evaluate in the original globals plus closure cells + helpers
    glb = dict(raw.__globals__)
    glb["_pt_if"] = _pt_if
    glb["_pt_while"] = _pt_while
    glb["_pt_not"] = _pt_not
    glb["_pt_and"] = _pt_and
    glb["_PT_UNDEF"] = _PT_UNDEF
    if raw.__closure__:
        for name, cell in zip(raw.__code__.co_freevars, raw.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    code = compile(tree, filename=f"<dy2static {raw.__qualname__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, glb, ns)  # noqa: S102 - compiling the user's own source
    new_fn = ns[fdef.name]
    new_fn.__pt_rewritten__ = True  # "the AST fallback engaged" marker
    if raw.__defaults__:
        new_fn.__defaults__ = raw.__defaults__
    functools.update_wrapper(new_fn, raw)
    if bound_self is not None:
        return new_fn.__get__(bound_self, type(bound_self))
    return new_fn
