"""Autograd public API.

Reference: `python/paddle/autograd/` — `backward()`, `PyLayer` custom-grad
(`autograd/py_layer.py:21,192`), `paddle.grad` partial grads
(`imperative/partial_grad_engine.cc`), `paddle.no_grad`.
"""
from __future__ import annotations

import contextlib
import functools

import jax

from ..core import framework
from ..core import tape as tape_mod
from ..core.dispatch import dispatch
from ..core.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False):
    tape_mod.backward(tensors, grad_tensors, retain_graph=retain_graph,
                      create_graph=create_graph)


class no_grad(contextlib.ContextDecorator):
    """Context manager AND decorator, like paddle.no_grad."""

    def __enter__(self):
        self._cm = framework.no_grad_guard()
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._cm = framework.enable_grad_guard()
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)


def is_grad_enabled():
    return framework.grad_enabled()


def set_grad_enabled(mode: bool):
    framework._state.grad_enabled = bool(mode)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False, name=None):
    """paddle.grad: partial gradients of outputs wrt inputs.

    Reference: `imperative/partial_grad_engine.cc` PartialGradEngine.
    Implemented by running the tape backward with grad capture restricted to
    ``inputs``; the tape is retained unless retain_graph=False is explicit.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    rg = True if retain_graph is None else retain_graph

    # partial-grad semantics (reference PartialGradEngine): .grad of EVERY
    # variable is left untouched — the backward records exactly the grads
    # it writes, and we restore them afterwards (inputs included: their
    # result is returned, not left on .grad).
    saved_inputs = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    touched = []
    tape_mod.backward(list(outputs), grad_tensors=grad_outputs,
                      retain_graph=rg, create_graph=create_graph,
                      touched=touched)
    grads = []
    for t, _ in saved_inputs:
        g = t.grad
        if g is None and not allow_unused:
            from ..ops import zeros_like

            g = zeros_like(t)
        grads.append(g)
    # restore in reverse write order so repeated writes unwind correctly
    for t, old in reversed(touched):
        t.grad = old
    for t, old in saved_inputs:
        t.grad = old
    return grads


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op (reference `python/paddle/autograd/py_layer.py:21`).

    Subclass defines ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    over Tensors.  The backward is recorded on the tape as an opaque node.
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with framework.no_grad_guard():
            outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs_grad = framework.grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        if needs_grad:
            new_outs = [Tensor(o._array, stop_gradient=False) for o in outs]

            def vjp_fn(cots):
                if not isinstance(cots, tuple):
                    cots = (cots,)
                cot_tensors = [Tensor(c) for c in cots]
                with framework.no_grad_guard():
                    gin = cls.backward(ctx, *cot_tensors)
                if not isinstance(gin, (list, tuple)):
                    gin = [gin]
                arrays = []
                gi = iter(gin)
                for t in tensor_inputs:
                    g = next(gi, None)
                    arrays.append(None if g is None else g._array)
                return arrays

            node = tape_mod.TapeNode(
                vjp_fn, tensor_inputs, new_outs, out_is_tuple=len(new_outs) > 1
            )
            tape_mod.default_tape().record(node)
            outs = new_outs
        return outs[0] if single else tuple(outs)

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError
