"""Core runtime: Tensor over jax.Array, dtype/place/flags, autograd tape.

TPU-native replacement of reference layers 1-3 (platform / memory /
framework core, SURVEY.md §1): device identity is a Place resolving to a
`jax.Device`; memory management is delegated to PJRT (no allocator stack
needed — reference `memory/allocation/allocator_facade.h:38` becomes XLA's
buffer manager); the framework core is the dispatch+tape pair in place of
OperatorBase/OpRegistry per-kernel dispatch.
"""
from .dtype import (bfloat16, bool_, complex64, complex128, float16, float32,
                    float64, get_default_dtype, int8, int16, int32, int64,
                    set_default_dtype, uint8)
from .dispatch import clear_dispatch_cache, dispatch_stats
from .flags import get_flags, set_flags
from .place import (CPUPlace, CUDAPlace, Place, TPUPlace, XPUPlace, get_device,
                    is_compiled_with_tpu, set_device)
from .tensor import Tensor, to_tensor
from .framework import seed
