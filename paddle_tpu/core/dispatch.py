"""Eager op dispatch with a signature-keyed compiled-executable cache.

Reference hot path: `core.ops.*` generated pybind functions →
`imperative::Tracer::TraceOp` (`imperative/tracer.cc:144`) → cached kernel
dispatch via the OpKernelMap → optional grad-node creation
(`tracer.cc:231`).  The reference never re-derives an op's kernel or grad
op per call: both are looked up from signature-keyed caches.

TPU-native replacement: every op is a pure jnp/lax function.  ``dispatch``
keys each call on ``(jfn identity, closed-over statics, static_kwargs,
input shapes/dtypes, diff positions, amp state)`` and memoizes

* a ``jax.jit``-compiled forward for the no-grad path, and
* a jitted forward + jitted vjp pair for the grad path (the pullback
  re-derives ``jax.vjp`` *inside* its own compiled executable, so XLA DCEs
  whatever part of the forward the cotangent doesn't need),

so a steady-state eager loop runs compiled executables with zero Python
retracing — the moral equivalent of the reference's OpKernelMap cache.
AMP autocast (reference `imperative/amp_auto_cast.cc`) is folded into the
traced computation and into the cache key instead of running as a
per-call Python pass.  Calls whose closures capture live arrays (dropout
keys, fancy indices) or that happen under a jit trace bypass the cache
and take the legacy per-call path.

Telemetry: per-op counters (calls, cache hits/misses/bypasses, retraces,
wall time) are collected on every dispatch and exposed through
``dispatch_stats`` / ``paddle_tpu.profiler``; ``FLAGS_eager_dispatch_report``
prints the table at interpreter exit.  The cache is LRU-bounded
(``FLAGS_eager_cache_size``) and can be dropped wholesale with
``clear_dispatch_cache()`` for shape-polymorphic workloads.
"""
from __future__ import annotations

import atexit
import functools
import os
import struct
import threading
import time
import types
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from . import flags, framework
from ..analysis.sanitizer import TrackedLock as _TrackedLock
from .tape import TapeNode, default_tape
from .tensor import Tensor

# AMP op policies, mirroring the reference white/black lists
# (`imperative/amp_auto_cast.cc` AmpOperators): 'white' ops run in the
# autocast dtype (matmul/conv — MXU ops), 'black' ops are forced to fp32
# (softmax/norm/reductions where bf16 accumulation hurts).
WHITE = "white"
BLACK = "black"


def _autocast_arrays(arrays, policy, enabled=None, target_dtype=None):
    """Apply the white/black-list cast.  With explicit ``enabled``/
    ``target_dtype`` the thread-local AMP state is not consulted — the
    cached fast path bakes the state captured at key time into the traced
    computation instead of re-reading it per call."""
    if enabled is None:
        st = framework.amp_state()
        enabled, target_dtype = st.amp_enabled, st.amp_dtype
    if not enabled or policy is None:
        return arrays
    if policy == WHITE:
        target = target_dtype or jnp.bfloat16
        return [
            a.astype(target)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a
            for a in arrays
        ]
    if policy == BLACK:
        return [
            a.astype(jnp.float32)
            if hasattr(a, "dtype") and a.dtype in (jnp.bfloat16, jnp.float16)
            else a
            for a in arrays
        ]
    return arrays


# ---------------------------------------------------------------------------
# Cache keying.  A key must capture everything that changes the traced
# computation: the op function (its code + every closed-over static), the
# static kwargs, each input's abstract signature (or concrete value for
# python scalars, which ops branch on), the differentiable positions, and
# the AMP state.  Anything the fingerprinter cannot prove stable (live
# arrays in closures, arbitrary mutable objects) raises _Uncacheable and
# the call falls back to the legacy per-call path.
# ---------------------------------------------------------------------------
class _Uncacheable(Exception):
    pass


# callable types whose identity fully determines behavior (immutable
# wrappers around a function fixed at construction) — safe to key by id;
# any other callable instance could mutate state behind its id and must
# bypass the cache instead
_IDENT_CALLABLES = (
    types.BuiltinFunctionType, types.BuiltinMethodType,
    np.ufunc, jnp.ufunc, type(jax.jit(lambda: None)),
    jax.custom_jvp, jax.custom_vjp,
)

_MAX_FP_DEPTH = 12


def _fingerprint(v, pins, depth=0):
    """Hashable fingerprint of a static value.  Objects keyed by identity
    (code objects, module-level callables) are appended to ``pins`` and
    kept alive by the cache entry so CPython id reuse can never alias two
    different objects onto one live key."""
    if depth > _MAX_FP_DEPTH:
        raise _Uncacheable("closure nesting too deep")
    if v is None or v is Ellipsis:
        return v
    t = type(v)
    if t is float:
        # key floats by BIT PATTERN: == equality would alias -0.0 onto
        # +0.0 (wrong cached executable) and NaN would never equal its
        # own key (every call a fresh miss, unbounded duplicate entries)
        return ("f64", struct.pack("<d", v))
    if t is bool or t is int or t is str or t is bytes:
        return (t.__name__, v)
    if t is complex:
        return ("c128", struct.pack("<dd", v.real, v.imag))
    if t is tuple or t is list:
        return (t.__name__,
                tuple(_fingerprint(x, pins, depth + 1) for x in v))
    if t is dict:
        try:
            # keys are fingerprinted too: {1: v} and {True: v} must not
            # alias (1 == True under raw comparison)
            return ("d", tuple(sorted(
                (_fingerprint(k, pins, depth + 1),
                 _fingerprint(x, pins, depth + 1))
                for k, x in v.items())))
        except TypeError:
            # mixed-type keys don't sort — fall back, don't crash
            raise _Uncacheable("unsortable dict keys")
    if t is slice:
        return ("sl", _fingerprint(v.start, pins, depth + 1),
                _fingerprint(v.stop, pins, depth + 1),
                _fingerprint(v.step, pins, depth + 1))
    if isinstance(v, (jax.Array, jax.core.Tracer, np.ndarray, Tensor)):
        # live data in a closure/static (dropout PRNG keys, fancy-index
        # arrays): its value changes call to call — never cacheable
        raise _Uncacheable("array-valued static")
    if isinstance(v, np.dtype):
        return ("dt", v.str)
    if isinstance(v, np.generic):
        return ("np", v.dtype.str, v.tobytes())  # bit-exact (-0.0, NaN)
    if t is types.FunctionType:
        try:
            cells = tuple(_fingerprint(c.cell_contents, pins, depth + 1)
                          for c in (v.__closure__ or ()))
        except ValueError:  # empty cell
            raise _Uncacheable("unfilled closure cell")
        pins.append(v.__code__)
        return ("f", id(v.__code__),
                _fingerprint(v.__defaults__, pins, depth + 1),
                _fingerprint(v.__kwdefaults__, pins, depth + 1), cells)
    if t is functools.partial:
        return ("pt", _fingerprint(v.func, pins, depth + 1),
                _fingerprint(v.args, pins, depth + 1),
                _fingerprint(v.keywords, pins, depth + 1))
    if t is types.MethodType:
        # the receiver is arbitrary mutable state the id can't capture —
        # a later `self.attr = ...` would silently replay a stale
        # executable; bypass instead
        raise _Uncacheable("bound method in dispatch key")
    if isinstance(v, _IDENT_CALLABLES):
        # immutable callable wrappers fixed at module import (jnp.ufunc,
        # PjitFunction, builtins, custom_jvp/vjp): identity IS the
        # behavior; pinned so the id stays unique while the entry lives
        pins.append(v)
        return ("c", id(v))
    if isinstance(v, type):
        pins.append(v)
        return ("ty", id(v))
    if callable(v):
        # an arbitrary callable instance can mutate behind its id
        # (obj.scale = 3.0) — never cacheable
        raise _Uncacheable(f"stateful callable {t.__name__} in key")
    raise _Uncacheable(f"{t.__name__} in dispatch key")


def _op_name(jfn):
    code = getattr(jfn, "__code__", None)
    if code is not None:
        return (f"{os.path.basename(code.co_filename)}:"
                f"{code.co_firstlineno}:{code.co_name}")
    return getattr(jfn, "__name__", None) or type(jfn).__name__


def _fn_key(jfn, pins):
    """Fingerprint of the op function, with an allocation-light fast path
    for the overwhelmingly common shape: a plain function/lambda whose
    closure holds only primitives (axis ints, transpose bools, ...).
    Cell values are type-prefixed so `True`/`1`/`1.0` cannot alias."""
    if type(jfn) is types.FunctionType and jfn.__defaults__ is None \
            and jfn.__kwdefaults__ is None:
        code = jfn.__code__
        clo = jfn.__closure__
        if clo is None:
            pins.append(code)
            return id(code)
        cells = []
        try:
            for c in clo:
                v = c.cell_contents
                tv = type(v)
                if tv is float:
                    # bit pattern, not == (see _fingerprint): -0.0 and
                    # NaN must not alias/miss
                    cells.append(tv)
                    cells.append(struct.pack("<d", v))
                elif tv is bool or tv is int or tv is str or v is None:
                    cells.append(tv)
                    cells.append(v)
                else:
                    return _fingerprint(jfn, pins)
        except ValueError:
            raise _Uncacheable("unfilled closure cell")
        pins.append(code)
        return (id(code), tuple(cells))
    return _fingerprint(jfn, pins)


# per-type memo for classifying dispatch operands; a type's kind never
# changes, so the ABC __instancecheck__ walk runs once per type, not per
# call (jax.Array is an ABC — its isinstance costs ~0.5us)
_KIND_ARRAY, _KIND_TRACER, _KIND_STATIC = 1, 2, 3
_KIND_MEMO: dict = {}


def _kind(a):
    t = type(a)
    k = _KIND_MEMO.get(t)
    if k is None:
        if isinstance(a, jax.core.Tracer):
            k = _KIND_TRACER
        elif isinstance(a, (jax.Array, np.ndarray)):
            k = _KIND_ARRAY
        else:
            k = _KIND_STATIC
        _KIND_MEMO[t] = k
    return k


_INEXACT_MEMO: dict = {}


def _is_inexact(dt):
    r = _INEXACT_MEMO.get(dt)
    if r is None:
        r = bool(jnp.issubdtype(dt, jnp.inexact))
        _INEXACT_MEMO[dt] = r
    return r


# ---------------------------------------------------------------------------
# Telemetry (per-op counters; reference: the tracer's per-op RecordEvent
# aggregation in platform/profiler.cc, here specialized to dispatch).
# ---------------------------------------------------------------------------
class _OpStats:
    __slots__ = ("calls", "hits", "misses", "bypasses", "time_s")

    def __init__(self):
        self.calls = 0
        self.hits = 0
        self.misses = 0
        self.bypasses = 0
        self.time_s = 0.0

    def bump(self, calls=0, hits=0, misses=0, bypasses=0, time_s=0.0):
        """Apply one call's counter deltas atomically — the ONLY
        mutation path besides `_zero`, so a concurrent
        reset_dispatch_stats can never tear (or lose) an update.  One
        lock round per dispatch: callers batch their deltas."""
        with _STATS_LOCK:
            self.calls += calls
            self.hits += hits
            self.misses += misses
            self.bypasses += bypasses
            self.time_s += time_s

    def _zero(self):
        # _STATS_LOCK is an RLock so both reset paths (standalone and
        # under dispatch_stats' atomic read+reset hold) share this one
        # zeroing definition
        with _STATS_LOCK:
            self.calls = self.hits = self.misses = self.bypasses = 0
            self.time_s = 0.0

    def as_dict(self):
        return {"calls": self.calls, "hits": self.hits,
                "misses": self.misses, "retraces": self.misses,
                "bypasses": self.bypasses, "time_s": self.time_s}


_STATS: dict = {}
_STATS_LOCK = _TrackedLock(threading.RLock(), "dispatch._STATS_LOCK")


def _stats_for(name) -> _OpStats:
    s = _STATS.get(name)
    if s is None:
        with _STATS_LOCK:
            s = _STATS.setdefault(name, _OpStats())
    return s


def dispatch_stats(reset=False):
    """Per-op dispatch telemetry: ``{op: {calls, hits, misses, retraces,
    bypasses, time_s}}``.  A 'retrace' is a miss that traced + compiled a
    new executable pair; 'bypasses' count calls that took the legacy
    per-call path (uncacheable closure, jit trace in progress, or cache
    disabled).  ``reset=True`` is atomic with the read: a concurrent
    ``bump`` lands either in the returned snapshot or in the post-reset
    counters, never in neither."""
    with _STATS_LOCK:
        out = {k: v.as_dict() for k, v in _STATS.items()}
        if reset:
            for s in _STATS.values():
                s._zero()
    return out


def reset_dispatch_stats():
    # zero in place: live cache entries hold direct references to their
    # _OpStats, so dropping the dict would orphan their counters and
    # post-reset hits would never be visible again
    with _STATS_LOCK:
        for s in _STATS.values():
            s._zero()


def telemetry_series():
    """Dispatch telemetry in the observability registry's neutral shape:
    ``(kind, name, label_names, rows)`` per exported series, each row a
    ``((label_values,), value)`` pair keyed by op.  The registry's
    dispatch *view* (paddle_tpu.observability) renders these into the
    Prometheus/JSON exports at collection time — ``dispatch_stats``
    stays the storage and the public API."""
    with _STATS_LOCK:
        items = sorted((k, v.as_dict()) for k, v in _STATS.items())
    fields = (("counter", "paddle_dispatch_calls_total", "calls"),
              ("counter", "paddle_dispatch_hits_total", "hits"),
              ("counter", "paddle_dispatch_misses_total", "misses"),
              ("counter", "paddle_dispatch_bypasses_total", "bypasses"),
              ("counter", "paddle_dispatch_time_seconds_total", "time_s"))
    return [(kind, name, ("op",),
             [((op,), st[field]) for op, st in items])
            for kind, name, field in fields]


def dispatch_summary_string(sorted_key="time"):
    """Aggregated dispatch table (layout after the reference's
    PrintProfiler table)."""
    rows = sorted(dispatch_stats().items(),
                  key=lambda kv: -kv[1]["calls" if sorted_key == "calls"
                                        else "time_s"])
    lines = [
        "----------------------  Eager Dispatch Report  "
        "----------------------",
        f"{'Op':<36}{'Calls':>8}{'Hits':>8}{'Miss':>6}{'Bypass':>8}"
        f"{'HitRate':>9}{'Total(ms)':>11}{'Avg(us)':>9}",
    ]
    for name, s in rows:
        cached = s["hits"] + s["misses"]
        hit_rate = s["hits"] / cached if cached else 0.0
        avg_us = s["time_s"] / s["calls"] * 1e6 if s["calls"] else 0.0
        lines.append(
            f"{name:<36}{s['calls']:>8}{s['hits']:>8}{s['misses']:>6}"
            f"{s['bypasses']:>8}{hit_rate:>9.1%}{s['time_s']*1e3:>11.3f}"
            f"{avg_us:>9.1f}")
    return "\n".join(lines)


@atexit.register
def _report_at_exit():
    try:
        if _STATS and flags.flag("eager_dispatch_report"):
            print(dispatch_summary_string())
    except Exception:
        pass


# ---------------------------------------------------------------------------
# The cache proper: signature key -> compiled executable pair.
# ---------------------------------------------------------------------------
class _Entry:
    __slots__ = ("fwd", "bwd", "inexact_out", "out_protos", "out_is_tuple",
                 "pins", "stats", "_bwd_factory")

    def __init__(self, fwd, bwd_factory, pins, stats):
        self.fwd = fwd
        self.bwd = None  # built lazily: needs output protos from first run
        self._bwd_factory = bwd_factory
        self.inexact_out = None
        self.out_protos = None
        self.out_is_tuple = False
        self.pins = pins
        self.stats = stats

    def ensure_bwd(self, outs, out_is_tuple):
        if self.bwd is None and self._bwd_factory is not None:
            protos = tuple((tuple(t._array.shape), t._array.dtype)
                           for t in outs)
            self.out_protos = protos
            self.out_is_tuple = out_is_tuple
            self.inexact_out = tuple(
                i for i, p in enumerate(protos)
                if jnp.issubdtype(p[1], jnp.inexact))
            self.bwd = self._bwd_factory(protos, self.inexact_out,
                                         out_is_tuple)
        return self.bwd


_CACHE: "OrderedDict[tuple, _Entry]" = OrderedDict()
_CACHE_LOCK = _TrackedLock(threading.Lock(), "dispatch._CACHE_LOCK")


def clear_dispatch_cache():
    """Drop every memoized executable (reference: Tracer op-cache reset).
    Use between phases of shape-polymorphic workloads so stale signatures
    don't pin compiled programs; the next call per signature retraces."""
    with _CACHE_LOCK:
        _CACHE.clear()


# op functions read runtime flags at TRACE time (kernel policy knobs like
# FLAGS_use_pallas_layernorm), baking the value into the executable — a
# set_flags change must invalidate the cache or it would be silently
# ignored for already-cached signatures (the legacy path re-read flags
# per call)
flags.on_flags_changed(clear_dispatch_cache)


def dispatch_cache_size() -> int:
    return len(_CACHE)


def _cache_put(key, entry):
    with _CACHE_LOCK:
        _CACHE[key] = entry
        try:
            bound = int(flags.flag("eager_cache_size"))
        except Exception:
            bound = 4096
        while bound > 0 and len(_CACHE) > bound:
            _CACHE.popitem(last=False)


# marks an array position in input_proto; a private sentinel, NOT None —
# a literal None positional input must stay a baked scalar, not swallow a
# jit argument
_ARG_SLOT = object()


def _build_entry(jfn, static_kwargs, input_proto, diff_pos, amp, pins,
                 stats):
    """Compile-cache entry for one signature.

    ``input_proto`` is a per-position list: ``_ARG_SLOT`` marks an array
    position (fed as a jit argument), anything else is a baked python
    scalar (ops may branch on those, so they are trace-time constants).
    """
    policy, amp_enabled, amp_dtype = amp
    arr_pos = tuple(i for i, p in enumerate(input_proto)
                    if p is _ARG_SLOT)
    scalars = [None if p is _ARG_SLOT else p for p in input_proto]

    def full(*arr_args):
        vals = list(scalars)
        for p, v in zip(arr_pos, arr_args):
            vals[p] = v
        vals = _autocast_arrays(vals, policy, amp_enabled, amp_dtype)
        if static_kwargs:
            return jfn(*vals, **static_kwargs)
        return jfn(*vals)

    fwd = jax.jit(full)

    bwd_factory = None
    if diff_pos:
        def bwd_factory(out_protos, inexact_out, out_is_tuple):
            def bwd_impl(arr_args, cots):
                vals = list(scalars)
                for p, v in zip(arr_pos, arr_args):
                    vals[p] = v
                vals = _autocast_arrays(vals, policy, amp_enabled,
                                        amp_dtype)
                diff_vals = [vals[p] for p in diff_pos]

                def f_of_diff(*d):
                    vv = list(vals)
                    for p, v in zip(diff_pos, d):
                        vv[p] = v
                    if static_kwargs:
                        return jfn(*vv, **static_kwargs)
                    return jfn(*vv)

                _, vjp_fn = jax.vjp(f_of_diff, *diff_vals)
                full_cots = []
                k = 0
                for i, proto in enumerate(out_protos):
                    if i in inexact_out:
                        full_cots.append(cots[k])
                        k += 1
                    else:
                        # integer/bool outputs take float0 cotangents per
                        # jax.vjp's contract; constant inside the trace
                        full_cots.append(
                            np.zeros(proto[0], jax.dtypes.float0))
                return vjp_fn(tuple(full_cots) if out_is_tuple
                              else full_cots[0])

            return jax.jit(bwd_impl)

    return _Entry(fwd, bwd_factory, pins, stats)


class _CachedVjp:
    """Pullback backed by the entry's jitted vjp executable.  Holds the
    call's array operands (the reference's saved-for-backward inputs) and
    feeds them back with the cotangents — zero retracing on the backward
    pass too."""
    __slots__ = ("entry", "arr_vals")

    def __init__(self, entry, arr_vals):
        self.entry = entry
        self.arr_vals = arr_vals

    def __call__(self, cot):
        entry = self.entry
        cots = cot if isinstance(cot, tuple) else (cot,)
        inexact = tuple(cots[i] for i in entry.inexact_out)
        return entry.bwd(self.arr_vals, inexact)


def _make_primal(jfn, static_kwargs, raw_arrays, diff_pos, amp):
    """Per-call primal closure for double-grad (reference
    PartialGradEngine): a pure function of the differentiable inputs that
    re-applies the AMP cast captured at record time.  Kept as a plain
    closure (not the jitted executable) so `jax.vjp` in the create_graph
    replay sees the raw op graph."""
    policy, amp_enabled, amp_dtype = amp

    def primal_fn(*diff_args):
        vals = _autocast_arrays(list(raw_arrays), policy, amp_enabled,
                                amp_dtype)
        for p, v in zip(diff_pos, diff_args):
            vals[p] = v
        if static_kwargs:
            return jfn(*vals, **static_kwargs)
        return jfn(*vals)

    return primal_fn


def dispatch(jfn, *inputs, amp_policy=None, nondiff=(), **static_kwargs):
    """Execute ``jfn(*arrays, **static_kwargs)`` with autograd recording.

    ``inputs`` may be Tensors, arrays, or python scalars.  Tensor inputs
    are differentiable unless their position is listed in ``nondiff``
    (e.g. an integer index operand).  Returns Tensor or tuple of Tensors.

    Steady-state calls hit the signature-keyed executable cache; see the
    module docstring for the key layout and bypass conditions.
    """
    t0 = time.perf_counter()
    grad_on = framework.grad_enabled()
    cacheable = flags.flag("eager_jit_ops") and not framework.in_trace()

    # single classification pass: raw arrays, key signature, jit operands
    # and differentiable positions all fall out of one loop
    arrays = []
    sig = []
    arr_vals = []
    diff = []
    pins = []
    i = 0
    for x in inputs:
        if isinstance(x, Tensor):
            a = x._array
            arrays.append(a)
            k = _kind(a)
            if k == _KIND_ARRAY:
                arr_vals.append(a)
                sig.append((a.shape, a.dtype,
                            getattr(a, "weak_type", False)))
            else:
                cacheable = False
            if grad_on and not x.stop_gradient and i not in nondiff \
                    and _is_inexact(a.dtype):
                diff.append(i)
        else:
            arrays.append(x)
            if cacheable:
                tv = type(x)
                if tv is float:
                    sig.append(("s", tv, struct.pack("<d", x)))
                elif tv is bool or tv is int or tv is str or x is None:
                    sig.append(("s", tv, x))
                else:
                    k = _kind(x)
                    if k == _KIND_ARRAY:
                        arr_vals.append(x)
                        sig.append((x.shape, x.dtype,
                                    getattr(x, "weak_type", False)))
                    elif k == _KIND_TRACER:
                        cacheable = False
                    else:
                        try:
                            sig.append(("s", _fingerprint(x, pins)))
                        except _Uncacheable:
                            cacheable = False
        i += 1
    diff_pos = tuple(diff)

    if cacheable:
        try:
            key = (_fn_key(jfn, pins),
                   _fingerprint(static_kwargs, pins) if static_kwargs
                   else None,
                   tuple(sig), diff_pos, amp_policy)
        except _Uncacheable:
            cacheable = False

    if cacheable:
        if amp_policy is not None:
            amp_on, amp_dtype = framework.amp_sig()
            if amp_on:
                amp = (amp_policy, True, amp_dtype)
                key = key + (str(amp_dtype),)
            else:
                amp = (None, False, None)
        else:
            amp = (None, False, None)

        entry = _CACHE.get(key)
        if entry is None:
            stats = _stats_for(_op_name(jfn))
            input_proto = [_ARG_SLOT if _kind(a) == _KIND_ARRAY else a
                           for a in arrays]
            entry = _build_entry(jfn, static_kwargs, input_proto,
                                 diff_pos, amp, pins, stats)
            _cache_put(key, entry)
            hit = 0
        else:
            with _CACHE_LOCK:  # LRU touch races _cache_put's eviction
                try:
                    _CACHE.move_to_end(key)
                except KeyError:  # concurrent clear
                    pass
            stats = entry.stats
            hit = 1
        try:
            out = entry.fwd(*arr_vals)

            if not diff_pos:
                wrapped = _wrap_out(out, stop_gradient=True)
                if flags.flag("check_nan_inf"):
                    _check_nan_inf(wrapped if isinstance(wrapped, tuple)
                                   else (wrapped,))
                return wrapped

            wrapped = _wrap_out(out, stop_gradient=False)
            outs = wrapped if isinstance(wrapped, tuple) else (wrapped,)
            entry.ensure_bwd(outs, isinstance(wrapped, tuple))
            node = TapeNode(
                _CachedVjp(entry, tuple(arr_vals)),
                [inputs[p] for p in diff_pos],
                list(outs),
                out_is_tuple=isinstance(wrapped, tuple),
                primal_fn=_make_primal(jfn, static_kwargs, arrays,
                                       diff_pos, amp),
            )
            default_tape().record(node)
            if flags.flag("check_nan_inf"):
                _check_nan_inf(outs)
            return wrapped
        finally:
            # in a finally so an op that RAISES (NaN check, trace
            # error) still shows up in the table — the bypass path
            # counts its failures the same way
            stats.bump(calls=1, hits=hit, misses=1 - hit,
                       time_s=time.perf_counter() - t0)

    # ---- legacy per-call path (uncacheable / trace mode / disabled) -----
    stats = _stats_for(_op_name(jfn))
    try:
        return _dispatch_uncached(jfn, inputs, arrays, amp_policy,
                                  bool(diff_pos), diff_pos, static_kwargs)
    finally:
        stats.bump(calls=1, bypasses=1,
                   time_s=time.perf_counter() - t0)


def _dispatch_uncached(jfn, inputs, arrays, amp_policy, needs_grad,
                       diff_pos, static_kwargs):
    """The original per-call path: eager execution, `jax.vjp` re-derived
    per call.  Taken under jit traces (a nested pjit would corrupt the
    exported jaxpr), for uncacheable closures, and when the cache is
    disabled — and it is the behavioral reference the cached path must
    match bit-for-bit."""
    arrays = _autocast_arrays(arrays, amp_policy)

    if static_kwargs:
        fn = lambda *a: jfn(*a, **static_kwargs)  # noqa: E731
    else:
        fn = jfn

    if not needs_grad or not diff_pos:
        out = fn(*arrays)
        return _wrap_out(out, stop_gradient=True)

    const = list(arrays)

    def fn_of_diff(*diff_args):
        a = list(const)
        for p, v in zip(diff_pos, diff_args):
            a[p] = v
        return fn(*a)

    diff_arrays = [arrays[p] for p in diff_pos]
    out, vjp_fn = jax.vjp(fn_of_diff, *diff_arrays)

    wrapped = _wrap_out(out, stop_gradient=False)
    outs = wrapped if isinstance(wrapped, tuple) else (wrapped,)
    node = TapeNode(
        vjp_fn,
        [inputs[p] for p in diff_pos],
        list(outs),
        out_is_tuple=isinstance(wrapped, tuple),
        primal_fn=fn_of_diff,
    )
    default_tape().record(node)

    if flags.flag("check_nan_inf"):
        _check_nan_inf(outs)
    return wrapped


def _wrap_out(out, stop_gradient):
    if isinstance(out, tuple):
        return tuple(Tensor(o, stop_gradient=stop_gradient) for o in out)
    return Tensor(out, stop_gradient=stop_gradient)


def _check_nan_inf(outs):
    # reference: FLAGS_check_nan_inf → CheckVarHasNanOrInf
    # (`framework/details/nan_inf_utils.h:29`)
    for t in outs:
        a = t._array
        if jnp.issubdtype(a.dtype, jnp.inexact) and not framework.in_trace():
            if bool(jnp.any(~jnp.isfinite(a))):
                raise FloatingPointError(
                    f"NaN or Inf detected in op output (shape={a.shape})"
                )
