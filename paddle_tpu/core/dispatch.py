"""Eager op dispatch.

Reference hot path: `core.ops.*` generated pybind functions →
`imperative::Tracer::TraceOp` (`imperative/tracer.cc:144`) → kernel dispatch →
optional grad-node creation (`tracer.cc:231`).

TPU-native replacement: every op is a pure jnp/lax function.  ``dispatch``
executes it eagerly (XLA compiles+caches each unique op/shape signature), and
when any differentiable input requires grad it runs the op under ``jax.vjp``
and records the pullback on the tape — the moral equivalent of
CreateGradOpNode, with JAX deriving the grad op instead of a hand-registered
GradOpMaker.  AMP autocast (reference `imperative/amp_auto_cast.cc`) is
applied here for ops that declare a cast policy.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import flags, framework
from .tape import TapeNode, default_tape
from .tensor import Tensor

# AMP op policies, mirroring the reference white/black lists
# (`imperative/amp_auto_cast.cc` AmpOperators): 'white' ops run in the
# autocast dtype (matmul/conv — MXU ops), 'black' ops are forced to fp32
# (softmax/norm/reductions where bf16 accumulation hurts).
WHITE = "white"
BLACK = "black"


def _autocast_arrays(arrays, policy):
    st = framework.amp_state()
    if not st.amp_enabled or policy is None:
        return arrays
    if policy == WHITE:
        target = st.amp_dtype or jnp.bfloat16
        return [
            a.astype(target)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a
            for a in arrays
        ]
    if policy == BLACK:
        return [
            a.astype(jnp.float32)
            if hasattr(a, "dtype") and a.dtype in (jnp.bfloat16, jnp.float16)
            else a
            for a in arrays
        ]
    return arrays


def dispatch(jfn, *inputs, amp_policy=None, nondiff=(), **static_kwargs):
    """Execute ``jfn(*arrays, **static_kwargs)`` with autograd recording.

    ``inputs`` may be Tensors, arrays, or python scalars.  Tensor inputs are
    differentiable unless their position is listed in ``nondiff`` (e.g. an
    integer index operand).  Returns Tensor or tuple of Tensors.
    """
    tensors = [x for x in inputs if isinstance(x, Tensor)]
    arrays = [x._array if isinstance(x, Tensor) else x for x in inputs]
    arrays = _autocast_arrays(arrays, amp_policy)

    needs_grad = framework.grad_enabled() and any(
        not t.stop_gradient for t in tensors
    )

    if static_kwargs:
        fn = lambda *a: jfn(*a, **static_kwargs)
    else:
        fn = jfn

    if not needs_grad:
        out = fn(*arrays)
        return _wrap_out(out, stop_gradient=True)

    # positions of differentiable inputs
    diff_pos = [
        i
        for i, x in enumerate(inputs)
        if isinstance(x, Tensor) and i not in nondiff
        and jnp.issubdtype(x._array.dtype, jnp.inexact)
    ]
    if not diff_pos:
        out = fn(*arrays)
        return _wrap_out(out, stop_gradient=True)

    const = list(arrays)

    def fn_of_diff(*diff_args):
        a = list(const)
        for p, v in zip(diff_pos, diff_args):
            a[p] = v
        return fn(*a)

    diff_arrays = [arrays[p] for p in diff_pos]
    out, vjp_fn = jax.vjp(fn_of_diff, *diff_arrays)

    wrapped = _wrap_out(out, stop_gradient=False)
    outs = wrapped if isinstance(wrapped, tuple) else (wrapped,)
    node = TapeNode(
        vjp_fn,
        [inputs[p] for p in diff_pos],
        list(outs),
        out_is_tuple=isinstance(wrapped, tuple),
        primal_fn=fn_of_diff,
    )
    default_tape().record(node)

    if flags.flag("check_nan_inf"):
        _check_nan_inf(outs)
    return wrapped


def _wrap_out(out, stop_gradient):
    if isinstance(out, tuple):
        return tuple(Tensor(o, stop_gradient=stop_gradient) for o in out)
    return Tensor(out, stop_gradient=stop_gradient)


def _check_nan_inf(outs):
    # reference: FLAGS_check_nan_inf → CheckVarHasNanOrInf
    # (`framework/details/nan_inf_utils.h:29`)
    for t in outs:
        a = t._array
        if jnp.issubdtype(a.dtype, jnp.inexact) and not framework.in_trace():
            if bool(jnp.any(~jnp.isfinite(a))):
                raise FloatingPointError(
                    f"NaN or Inf detected in op output (shape={a.shape})"
                )
