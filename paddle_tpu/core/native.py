"""ctypes binding to the native runtime (libpaddle_tpu_rt.so, csrc/).

The native layer provides the C++ substrate that the reference implements in
`paddle/fluid/platform` + `memory` + `framework/details` (SURVEY.md §2.1):

* ``Arena``        — auto-growth best-fit host staging allocator
                     (reference AutoGrowthBestFitAllocator,
                     memory/allocation/auto_growth_best_fit_allocator.h:29)
* ``ThreadPool`` / ``TaskGraph`` — dependency-counted DAG scheduler
                     (reference FastThreadedSSAGraphExecutor,
                     framework/details/fast_threaded_ssa_graph_executor.h:32)
* ``PrefetchQueue`` — background batch prefetcher
                     (reference buffered_reader.cc / reader_py.cc)
* flags / stats / tracer — platform/flags.cc, monitor.cc, profiler.h

Build: ``cmake -B build -G Ninja csrc && ninja -C build``.  If the shared
library is absent this module builds it on first import (g++ toolchain is a
baked-in dependency); all consumers degrade gracefully through
``native_available()``.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_LIB_CANDIDATES = (
    # source-tree builds first so a rebuild is never shadowed by a stale
    # packaged copy; the packaged location (setup.py puts the lib there
    # for wheels) is the fallback when no source build exists
    os.path.join(_REPO_ROOT, "build", "libpaddle_tpu_rt.so"),
    os.path.join(_REPO_ROOT, "csrc", "libpaddle_tpu_rt.so"),
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "lib", "libpaddle_tpu_rt.so"),
)

_lib = None
_lib_lock = threading.Lock()


def _try_build() -> str | None:
    """Build the native library in-tree (best effort, quiet)."""
    src = os.path.join(_REPO_ROOT, "csrc")
    build = os.path.join(_REPO_ROOT, "build")
    if not os.path.isdir(src):
        return None
    try:
        subprocess.run(["cmake", "-B", build, "-G", "Ninja", src],
                       check=True, capture_output=True, timeout=120)
        subprocess.run(["ninja", "-C", build], check=True,
                       capture_output=True, timeout=300)
    except Exception:
        return None
    path = os.path.join(build, "libpaddle_tpu_rt.so")
    return path if os.path.exists(path) else None


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            # False = a previous attempt failed; don't re-run cmake/ninja on
            # every facade call.
            return None if _lib is False else _lib
        path = next((p for p in _LIB_CANDIDATES if os.path.exists(p)), None)
        if path is None:
            path = _try_build()
        if path is None:
            _lib = False
            return None
        lib = ctypes.CDLL(path)
        # ---- signatures ----
        lib.ptrt_arena_create.restype = ctypes.c_void_p
        lib.ptrt_arena_create.argtypes = [ctypes.c_size_t]
        lib.ptrt_arena_destroy.argtypes = [ctypes.c_void_p]
        lib.ptrt_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                         ctypes.POINTER(ctypes.c_void_p)]
        lib.ptrt_arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.ptrt_arena_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_size_t)] * 3

        lib.ptrt_last_error_message.restype = ctypes.c_char_p
        lib.ptrt_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.ptrt_flag_get.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_size_t]
        lib.ptrt_stat_add.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.ptrt_stat_value.argtypes = [ctypes.c_char_p]
        lib.ptrt_stat_value.restype = ctypes.c_int64

        lib.ptrt_now_ns.restype = ctypes.c_uint64
        lib.ptrt_trace_record.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_uint64]
        lib.ptrt_trace_export.restype = ctypes.c_size_t
        lib.ptrt_trace_export.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.ptrt_trace_count.restype = ctypes.c_size_t

        lib.ptrt_pool_create.restype = ctypes.c_void_p
        lib.ptrt_pool_create.argtypes = [ctypes.c_int]
        lib.ptrt_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.ptrt_pool_size.argtypes = [ctypes.c_void_p]
        lib.ptrt_graph_create.restype = ctypes.c_void_p
        lib.ptrt_graph_destroy.argtypes = [ctypes.c_void_p]
        lib.ptrt_graph_add_node.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                            ctypes.c_void_p]
        lib.ptrt_graph_add_edge.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.c_int]
        lib.ptrt_graph_run.argtypes = [ctypes.c_void_p, ctypes.c_void_p]

        lib.ptrt_prefetch_create.restype = ctypes.c_void_p
        lib.ptrt_prefetch_create.argtypes = [
            ctypes.c_size_t, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int]
        lib.ptrt_prefetch_destroy.argtypes = [ctypes.c_void_p]
        lib.ptrt_prefetch_pop.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int64)]
        lib.ptrt_prefetch_shutdown.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _check(rc: int):
    if rc != 0:
        lib = _load()
        raise RuntimeError(
            f"native runtime error {rc}: "
            f"{lib.ptrt_last_error_message().decode()}")


# ---------------------------------------------------------------------------
# Python wrappers
# ---------------------------------------------------------------------------
class Arena:
    """Best-fit auto-growth host arena (see csrc/allocator.cc)."""

    def __init__(self, chunk_size: int = 64 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.ptrt_arena_create(chunk_size)

    def alloc(self, size: int) -> int:
        out = ctypes.c_void_p()
        _check(self._lib.ptrt_arena_alloc(self._h, size, ctypes.byref(out)))
        return out.value

    def free(self, ptr: int):
        _check(self._lib.ptrt_arena_free(self._h, ptr))

    def buffer(self, ptr: int, size: int) -> memoryview:
        """Zero-copy view over an arena allocation (for numpy frombuffer)."""
        return memoryview((ctypes.c_char * size).from_address(ptr))

    def stats(self) -> dict:
        a, b, c = (ctypes.c_size_t(), ctypes.c_size_t(), ctypes.c_size_t())
        self._lib.ptrt_arena_stats(self._h, ctypes.byref(a), ctypes.byref(b),
                                   ctypes.byref(c))
        return {"in_use": a.value, "peak": b.value, "reserved": c.value}

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ptrt_arena_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_NODE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class TaskGraph:
    """Dependency-counted DAG run on a native thread pool."""

    def __init__(self, n_threads: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._pool = lib.ptrt_pool_create(n_threads)
        self._g = lib.ptrt_graph_create()
        self._cbs = []  # keep trampolines alive

    def add_node(self, fn) -> int:
        cb = _NODE_CB(lambda _ud: fn())
        self._cbs.append(cb)
        return self._lib.ptrt_graph_add_node(
            self._g, ctypes.cast(cb, ctypes.c_void_p), None)

    def add_edge(self, src: int, dst: int):
        _check(self._lib.ptrt_graph_add_edge(self._g, src, dst))

    def run(self):
        _check(self._lib.ptrt_graph_run(self._g, self._pool))

    def close(self):
        if getattr(self, "_g", None):
            self._lib.ptrt_graph_destroy(self._g)
            self._lib.ptrt_pool_destroy(self._pool)
            self._g = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_PRODUCER_CB = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
    ctypes.POINTER(ctypes.c_size_t), ctypes.c_void_p)


class PrefetchQueue:
    """Background prefetcher over a Python producer.

    ``producer(index) -> bytes | None`` runs on native worker threads
    (ctypes releases the GIL around pops, producers re-acquire it); returned
    byte payloads are copied into arena storage owned by the queue consumer.
    """

    def __init__(self, producer, capacity: int = 4, n_workers: int = 1,
                 ordered: bool = True, arena: Arena | None = None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._arena = arena or Arena(16 << 20)
        self._producer = producer
        self._error = None  # first producer exception, re-raised in pop()
        self._outstanding = set()  # arena ptrs handed to the queue, not yet popped

        def _produce(index, out_data, out_size, _ud):
            try:
                payload = producer(index)
            except Exception as e:  # noqa: BLE001 — surfaced via pop()
                if self._error is None:
                    self._error = e
                return 1
            if payload is None:
                return 1
            buf = bytes(payload)
            ptr = self._arena.alloc(len(buf))
            ctypes.memmove(ptr, buf, len(buf))
            self._outstanding.add(ptr)
            out_data[0] = ptr
            out_size[0] = len(buf)
            return 0

        self._cb = _PRODUCER_CB(_produce)
        self._h = lib.ptrt_prefetch_create(
            capacity, n_workers, ctypes.cast(self._cb, ctypes.c_void_p),
            None, 1 if ordered else 0)

    def pop(self) -> bytes | None:
        data = ctypes.c_void_p()
        size = ctypes.c_size_t()
        index = ctypes.c_int64()
        ok = self._lib.ptrt_prefetch_pop(self._h, ctypes.byref(data),
                                         ctypes.byref(size),
                                         ctypes.byref(index))
        if not ok:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return None
        out = ctypes.string_at(data.value, size.value)
        self._outstanding.discard(data.value)
        self._arena.free(data.value)
        return out

    def close(self):
        if getattr(self, "_h", None):
            # shutdown joins workers, so no producer callback is running
            # after it returns; safe to release batches never popped.
            self._lib.ptrt_prefetch_shutdown(self._h)
            self._lib.ptrt_prefetch_destroy(self._h)
            self._h = None
            for ptr in self._outstanding:
                self._arena.free(ptr)
            self._outstanding.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# flags / stats / tracing module-level facade
# ---------------------------------------------------------------------------
def flag_set(key: str, value) -> None:
    lib = _load()
    if lib is None:
        return
    lib.ptrt_flag_set(key.encode(), str(value).encode())


def flag_get(key: str, default=None):
    lib = _load()
    if lib is None:
        return default
    buf = ctypes.create_string_buffer(4096)
    if not lib.ptrt_flag_get(key.encode(), buf, len(buf)):
        return default
    return buf.value.decode()


def stat_add(key: str, value: int) -> None:
    lib = _load()
    if lib is not None:
        lib.ptrt_stat_add(key.encode(), int(value))


def stat_value(key: str) -> int:
    lib = _load()
    return 0 if lib is None else int(lib.ptrt_stat_value(key.encode()))


def tracer_enable():
    lib = _load()
    if lib is not None:
        lib.ptrt_tracer_enable()


def tracer_disable():
    lib = _load()
    if lib is not None:
        lib.ptrt_tracer_disable()


def trace_record(name: str, start_ns: int, dur_ns: int):
    lib = _load()
    if lib is not None:
        lib.ptrt_trace_record(name.encode(), start_ns, dur_ns)


def trace_clear():
    lib = _load()
    if lib is not None:
        lib.ptrt_trace_clear()


def now_ns() -> int:
    lib = _load()
    if lib is None:
        import time
        return time.monotonic_ns()
    return int(lib.ptrt_now_ns())


def trace_export_json() -> str:
    lib = _load()
    if lib is None:
        return '{"traceEvents":[]}'
    n = lib.ptrt_trace_export(None, 0)
    buf = ctypes.create_string_buffer(n)
    lib.ptrt_trace_export(buf, n)
    return buf.value.decode()


class RecordEvent:
    """RAII trace annotation (reference platform/profiler.h RecordEvent)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._t0 = now_ns()
        return self

    def __exit__(self, *exc):
        trace_record(self.name, self._t0, now_ns() - self._t0)
        return False
