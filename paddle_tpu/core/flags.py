"""Global runtime flag registry.

Reference: gflags knobs in `paddle/fluid/platform/flags.cc:33-603` exposed to
Python through `pybind/global_value_getter_setter.cc` as
`paddle.set_flags`/`get_flags`.  Here flags are a plain process-global
registry; flags may also be seeded from the environment as ``FLAGS_<name>``.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable

_REGISTRY: Dict[str, Any] = {}

# invalidation hooks: traced-executable caches bake flag values read at
# trace time (e.g. FLAGS_use_pallas_layernorm inside a dispatched op), so
# a flag change must drop them or set_flags would be silently ignored for
# already-cached signatures
_ON_CHANGE = []


def on_flags_changed(callback):
    _ON_CHANGE.append(callback)


def define_flag(name: str, default, help_str: str = ""):
    env = os.environ.get(f"FLAGS_{name}")
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = value


def set_flags(flags: Dict[str, Any]):
    # validate every key BEFORE mutating: a partial apply that raised on
    # a later unknown key would skip the invalidation callbacks below,
    # leaving cached executables replaying the old value of the flags
    # that did change
    items = [(k[len("FLAGS_"):] if k.startswith("FLAGS_") else k, v)
             for k, v in flags.items()]
    for k, _ in items:
        if k not in _REGISTRY:
            raise KeyError(f"unknown flag {k!r}")
    changed = False
    for k, v in items:
        if _REGISTRY[k] != v:
            changed = True
        _REGISTRY[k] = v
    if changed:
        for cb in _ON_CHANGE:
            cb()


def get_flags(names) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    out = {}
    for k in names:
        key = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        out[k] = _REGISTRY[key]
    return out


def flag(name: str):
    return _REGISTRY[name]


# Core flags (subset of reference's platform/flags.cc that is meaningful on
# TPU; CUDA/cudnn-specific knobs are intentionally absent).
define_flag("check_nan_inf", False,
            "check every op output for NaN/Inf (debug only: forces a host "
            "sync per op, serializing the device)")
define_flag("benchmark", False, "sync + log after every eager op")
define_flag("deterministic", False, "force deterministic reductions")
define_flag("eager_jit_ops", True,
            "enable the signature-keyed eager dispatch cache (jitted "
            "fwd/vjp executables memoized per op signature; off = legacy "
            "per-call tracing)")
define_flag("eager_cache_size", 4096,
            "LRU bound on memoized dispatch executables (<=0 = unbounded); "
            "shape-polymorphic loops should also call "
            "clear_dispatch_cache() between phases")
define_flag("eager_dispatch_report", False,
            "print the per-op dispatch telemetry table (calls, cache "
            "hits/misses, retraces, wall time) at interpreter exit")
define_flag("amp_dtype", "bfloat16", "autocast compute dtype (TPU: bfloat16)")
define_flag("allocator_strategy", "pjrt", "memory is managed by PJRT")
define_flag("log_level", 0, "VLOG-style verbosity")
define_flag("use_pallas_attention", "auto",
            "attention kernel policy: auto (seq threshold), 1 force, 0 off")
define_flag("pallas_attention_min_seq", 512,
            "sequence length at/above which 'auto' picks the Pallas kernel "
            "(measured crossover vs XLA on v5e: see BENCH_kernels.json; "
            "round 3's causal dead-block DMA clamps moved it 1024 -> 512)")
define_flag("use_pallas_layernorm", False,
            "use the Pallas fused layer_norm kernel instead of XLA fusion")
define_flag("interp_tensor_array_capacity", 0,
            "fallback capacity for TensorArrays written inside an "
            "interpreted `while` when the loop bound cannot be inferred "
            "from the Condition (0 = raise instead)")
define_flag("chunked_prefill", True,
            "serving engine prefill policy: 1 (default) fuses prompt "
            "ingestion into the decode step — each step feeds every "
            "prefilling slot a prompt chunk and every decoding slot its "
            "usual token through ONE mixed-batch executable, so an "
            "admission never stalls running decodes for a full prompt "
            "pass.  0 restores the legacy one-shot bucket-padded prefill "
            "(the greedy-parity oracle; see docs/DECODE_PERF.md)")
define_flag("prefill_chunk_tokens", 64,
            "per-step prompt-token budget of the chunked-prefill "
            "scheduler (FLAGS_chunked_prefill): each engine step consumes "
            "at most this many prompt tokens across all prefilling slots "
            "(a single slot's chunk is also capped here — it is the Q_max "
            "of the fixed-shape mixed-step executable).  Smaller values "
            "bound per-step latency (TPOT of running requests) tighter at "
            "the cost of more steps to finish a prompt")
define_flag("prefix_cache", True,
            "serving-engine prefix caching (chunked prefill only): full "
            "prompt KV pages are content-addressed by a chain hash "
            "(rolling per-page digest keyed by a sampling-invariant "
            "model fingerprint) and reused across requests at "
            "refcount+1 — admission maps the longest page-aligned "
            "cached prefix into the request's block table and chunked "
            "prefill starts at the first novel token; a mid-page "
            "divergence recomputes into a fresh copy-on-write page "
            "(cached pages are never written in place), and refcount-"
            "zero cached pages are retained on an LRU and evicted "
            "least-recently-released-first under pool pressure.  0 "
            "restores prefill-from-scratch bit-exactly (the parity "
            "oracle; see docs/DECODE_PERF.md)")
define_flag("kv_quant", "off",
            "serving KV-page storage quantization "
            "(inference.serving.DecodeEngine): 'int8' stores K/V pages "
            "as int8 with per-page, per-head symmetric scales in "
            "parallel donated f32 arrays — half/quarter the bytes per "
            "page means proportionally more concurrent slots at fixed "
            "pool memory; dequantization fuses into the paged-"
            "attention K/V loads (Pallas kernel: in-register after the "
            "page DMA, scale rows scalar-prefetched with the block "
            "tables) and the write path quantizes each scattered "
            "chunk in-graph, folding its per-head absmax into the "
            "running page scale (existing rows re-quantize when the "
            "scale grows — the 'refold').  'off' (default) is the "
            "bit-exact full-precision path and constructs the exact "
            "same executables as before the feature existed.  Output "
            "quality is gated by measurement, not just plumbing: see "
            "tools/bench_kv_quant.py / docs/DECODE_PERF.md.  Engines "
            "constructed with an explicit kv_quant= ignore the flag")
define_flag("serve_weights", "off",
            "serving weight-storage quantization "
            "(inference.serving.DecodeEngine): 'int8' folds every "
            "matmul weight of the step executables — qkv/out/fc1/fc2 "
            "projections, the untied LM head, and a bound draft "
            "model's weights — to per-out-channel symmetric int8 "
            "(quantization.int8.quantize_weight) with f32 scales in "
            "parallel `*_q`/`*_s` param leaves; embeddings, position "
            "tables, layernorms and biases stay f32.  The matmul sites "
            "dequantize fused at use (mixed f32xs8 dot + scale in the "
            "dot epilogue), so weights stream from HBM as int8 — ~4x "
            "less weight traffic per step on the bandwidth-bound "
            "decode path.  'off' (default) is the bit-exact "
            "full-precision path and constructs the exact same "
            "executables as before the feature existed.  Output "
            "quality is gated by measurement, not just plumbing: see "
            "tools/bench_wquant.py / docs/INT8_PERF.md.  Engines "
            "constructed with an explicit serve_weights= ignore the "
            "flag")
define_flag("snapshot_kv", True,
            "serialize the content-addressed (prefix-cached) KV page "
            "payloads — int8 + scales under FLAGS_kv_quant — into a "
            "crc-validated sidecar (kv_pages.npz) beside each "
            "durability snapshot: durability.restore_from_dir installs "
            "them into the fresh pool and registers their chain "
            "hashes, so replay re-admission prefix-hits the installed "
            "pages instead of recomputing the whole prompt (and a "
            "quantized snapshot is a fraction of the fp32 bytes).  A "
            "missing/torn sidecar falls back to full recompute — "
            "restores stay bit-identical either way.  0 = snapshot "
            "host state only, as before")
define_flag("cache_generated_pages", False,
            "content-address GENERATED full KV pages as decode "
            "crosses page boundaries (requires FLAGS_prefix_cache): "
            "the prompt's chain hash extends over the generated "
            "tokens, so beam/agent fanout sharing a DECODE prefix — "
            "and the fleet router's prefix-affinity key — prefix-hit "
            "the generated region too, not just the prompt.  0 "
            "(default) registers prompt pages only: pool occupancy "
            "and eviction order are bit-exact with the pre-fleet "
            "engine (the parity oracle tests/test_prefix_cache.py "
            "pins).  Engines constructed with an explicit "
            "cache_generated_pages= ignore the flag")
define_flag("kv_pool_debug", False,
            "audit KVBlockPool consistency (free/private/cached page "
            "partition, refcounts vs live request holds, eviction-LRU "
            "membership) at every DecodeEngine step boundary — debug "
            "only, adds host-side cost per step")
define_flag("sched_policy", "fifo",
            "serving-engine admission scheduler "
            "(inference.frontend.make_scheduler): 'fifo' (default) "
            "admits in strict arrival order — bit-exact with the "
            "historical behavior, never preempts; 'slo' orders by "
            "priority class then earliest-deadline-first, expires "
            "still-queued requests past their deadline_ms, skips a "
            "head-of-line blocker when a smaller request behind it "
            "fits (bounded by an anti-starvation fence), and under "
            "slot/pool pressure preempts the lowest-priority running "
            "request for resume via the prefix cache.  Engines "
            "constructed with an explicit scheduler ignore the flag")
define_flag("spec_decode_k", 0,
            "speculative decoding draft length for the serving engine "
            "(inference.serving.DecodeEngine): propose K tokens per step "
            "and verify them in one multi-query pass (0 = off, classic "
            "one-token-per-step decode).  Engines constructed with an "
            "explicit spec_decode_k ignore the flag")
define_flag("spec_drafter", "prompt_lookup",
            "drafter the engine builds when speculative decoding is on "
            "and no Drafter instance is passed: 'prompt_lookup' (model-"
            "free n-gram lookup over each request's own token history; "
            "see inference.speculative.PromptLookupDrafter).  A draft-"
            "model drafter must be passed as an instance (it needs the "
            "draft GPT's weights)")
define_flag("ragged_step", False,
            "unified ragged serving step (inference.serving."
            "DecodeEngine): decode, mixed prefill+decode, and "
            "speculative-verify traffic all dispatch ONE step "
            "executable whose rows each carry their own query span "
            "(decode=1, prefill chunk=C, verify window=K+1) instead "
            "of three phase-split executables per KV mode.  Greedy "
            "tokens are bit-identical to the split path (the off "
            "path compiles the exact same executables as before and "
            "stays the parity oracle).  Engines constructed with an "
            "explicit ragged_step ignore the flag")
define_flag("serve_mesh", "",
            "tensor-parallel serving mesh spec for inference.serving."
            "DecodeEngine, e.g. 'mp=2' or 'mp=4': the engine builds a "
            "Mesh over that many devices, shards params by the regex "
            "partition rules in parallel.partition (column-split "
            "qkv/fc1, row-split out/fc2, replicated norms/embeddings) "
            "and shards the KV page pool on the head axis (each chip "
            "holds its head-slice of every page; block tables and the "
            "page allocator stay host-global).  Implies the unified "
            "ragged step — the mesh shards the ONE step executable "
            "per KV mode.  Greedy tokens stay token-identical to the "
            "single-chip engine; '' (default) = single-chip path, "
            "bit-exact, zero sharding machinery touched.  Engines "
            "constructed with an explicit serve_mesh ignore the flag")
define_flag("spec_adaptive_k", False,
            "adaptive per-slot speculation depth (inference."
            "speculative.SpeculativeDecoder): each slot's draft "
            "length starts at the configured spec_decode_k, halves "
            "toward spec_k_min after spec_k_shrink_streak fully-"
            "rejected rounds, and grows back one step after "
            "spec_k_grow_streak fully-accepted rounds (growth is "
            "additionally gated by the cost model's per-kind "
            "calibration when armed).  Per-slot K only narrows a "
            "row's span on the already-compiled verify window — no "
            "new executable shapes.  Greedy tokens stay exactly the "
            "target model's.  Needs spec_decode_k >= 1")
define_flag("spec_k_min", 1,
            "adaptive-K floor (FLAGS_spec_adaptive_k): a slot's "
            "speculation depth never shrinks below this many drafted "
            "tokens — 1 keeps at least classic+1 emission potential "
            "while a drafter is cold")
define_flag("spec_k_shrink_streak", 2,
            "adaptive-K shrink trigger: consecutive verify rounds in "
            "which a slot accepted NONE of its drafts before its "
            "depth halves (multiplicative decrease)")
define_flag("spec_k_grow_streak", 2,
            "adaptive-K grow trigger: consecutive verify rounds in "
            "which a slot accepted EVERY usable draft before its "
            "depth grows by one (additive increase, capped at "
            "spec_decode_k)")
define_flag("metrics_report_interval_s", 0.0,
            "interval of the periodic observability reporter "
            "(paddle_tpu.observability.start_reporter): every interval a "
            "metrics snapshot is handed to the reporter sink on a daemon "
            "thread.  0 (default) = off.  DecodeEngine construction "
            "auto-starts the reporter when the flag is positive")
define_flag("sanitize", False,
            "serving sanitizer mode (paddle_tpu.analysis.sanitizer): "
            "warm retraces RAISE instead of counting, donated step "
            "buffers are tombstoned after every jitted call and any "
            "later host access raises naming the donation site, the "
            "designated telemetry locks record acquisition order (a "
            "lock-order cycle fails at the acquisition that would have "
            "deadlocked), KVBlockPool.assert_consistent runs at every "
            "DecodeEngine step boundary, and blocking device syncs "
            "inside the step span are counted.  Debug/CI only — adds "
            "host-side cost per step and per lock acquisition")
define_flag("fault_inject", "",
            "arm the serving fault-injection harness "
            "(inference.resilience.FaultPlan.parse): a "
            "';'-separated list of site@occurrences entries — e.g. "
            "'step@3,5;pool@2-4;drafter@1' injects a step-executable "
            "raise at the 3rd and 5th consult of the step site, pool "
            "exhaustion at alloc consults 2..4, and a drafter raise at "
            "its 1st consult — plus 'poison@TOKEN' (every step fails "
            "while a request whose prompt contains TOKEN is in the "
            "batch; the bisect containment must find it).  "
            "Deterministic: occurrence counters, never wall-clock.  "
            "Empty (default) = off, zero hooks on the hot path.  "
            "Engines constructed with an explicit fault_plan= ignore "
            "the flag")
define_flag("step_retries", 2,
            "same-step retries of a failed step executable before the "
            "containment ladder escalates (degrade the failing "
            "subsystem, then bisect-quarantine the suspect request; "
            "see docs/RELIABILITY.md).  Each retry backs off "
            "exponentially in deterministic ticks (1, 2, 4, ... capped "
            "at 8) and sleeps tick * FLAGS_step_backoff_ms")
define_flag("step_backoff_ms", 0.0,
            "wall-clock milliseconds per backoff tick between step "
            "retries (0 = count ticks but never sleep — the "
            "deterministic default tier-1 tests rely on)")
define_flag("degrade_after", 3,
            "consecutive failures of one subsystem (speculative "
            "drafter/verify, mixed prefill+decode executable) before "
            "the engine degrades it away — speculation disables, "
            "chunked prefill falls back to the legacy one-shot "
            "prefill oracle path (paddle_degraded_mode gauge flips)")
define_flag("degraded_probe_steps", 16,
            "clean engine steps in degraded mode before the engine "
            "probes re-enabling the degraded subsystem (speculation / "
            "chunked prefill); a fresh failure degrades it again")
define_flag("engine_recoveries", 2,
            "engine rebuilds (inference.resilience.recover: fresh "
            "engine, every in-flight request re-admitted with its "
            "generated tokens folded into the prompt for replay) the "
            "frontend driver / serve_with_recovery may spend before "
            "declaring the fault unrecoverable (DegradedMode)")
define_flag("journal_dir", "",
            "arm durable serving (inference.durability): directory for "
            "the append-only write-ahead request journal (one record "
            "per admission / emitted-token watermark / finish) plus "
            "periodic on-disk engine snapshots — "
            "durability.restore_from_dir rebuilds the engine in a "
            "FRESH process after a SIGKILL/OOM with zero request loss "
            "and no re-emitted stream tokens.  Empty (default) = off; "
            "every hook on the serve path is then one `is None` check")
define_flag("journal_fsync", "step",
            "journal durability policy: 'always' fsyncs after every "
            "record (strongest no-re-emission guarantee, one fsync per "
            "emit), 'step' (default) buffers and fsyncs once per "
            "engine step, 'never' flushes to the OS without fsync "
            "(survives process death, not power loss).  See "
            "docs/RELIABILITY.md for the trade-offs")
define_flag("snapshot_interval_steps", 32,
            "engine steps between on-disk EngineSnapshot serializations "
            "when FLAGS_journal_dir is armed; the snapshot bounds how "
            "much of the journal a restore must replay (and how many "
            "tokens it must recompute).  <= 0 disables periodic "
            "snapshots — restore then replays the whole journal")
define_flag("journal_compact", True,
            "rewrite the write-ahead journal during durability."
            "restore_from_dir: the compacted journal carries one cfg "
            "record plus one admission + one watermark per request "
            "still in flight (finished requests and superseded "
            "watermarks drop), and the snapshot is re-anchored to it "
            "— so a serve that restores N times starts each life from "
            "a bounded file instead of replaying every previous "
            "life's records (the journal_growth alert's failure "
            "mode).  0 = append to the historical journal unmodified, "
            "as before")
define_flag("compile_cache_dir", "",
            "directory for JAX's persistent compilation cache: a "
            "rebuilt engine in a FRESH process (durability."
            "restore_from_dir) warm-starts its executables from disk "
            "instead of recompiling.  Process-global (jax config), "
            "applied at the first engine construction that sees it")
define_flag("step_timeout_ms", 0.0,
            "hung-step watchdog (inference.durability.StepWatchdog): a "
            "DecodeEngine.step exceeding this wall-clock budget is "
            "classified hung — paddle_engine_health flips to 'hung' "
            "and a fatal HungStep routes the supervisor "
            "(serve_with_recovery / ServingFrontend._drive) through "
            "engine recovery; the frontend additionally abandons a "
            "worker thread still stuck past the budget.  Steps that "
            "compiled an executable are exempt (compiles are expected "
            "warmup stalls, not hangs).  0 (default) = disarmed")
define_flag("flight_window", 64,
            "serving flight recorder (observability.flight): number of "
            "per-step records the bounded ring buffer retains — one "
            "structured record per DecodeEngine.step (batch "
            "composition, phase-time breakdown, ladder events, pool "
            "occupancy, SLO burn).  Always-on and always-cheap by "
            "design; 0 disables the recorder entirely (statusz then "
            "serves engine state without flight history)")
define_flag("flight_dir", "",
            "directory for crash-safe flight-window auto-dumps (tmp+"
            "rename, same discipline as durability snapshots): every "
            "fatal StepFault, hung-step classification and watchdog "
            "abandonment leaves a black-box JSON the "
            "tools/explain_request.py timeline reconstructor reads.  "
            "Empty (default) = beside the journal "
            "(<journal_dir>/flight) when FLAGS_journal_dir is armed, "
            "else auto-dump is off (the in-memory ring and statusz "
            "still work)")
define_flag("cost_model", True,
            "serving cost observatory (observability.costmodel): "
            "extract a static FLOP/byte profile per compiled step "
            "executable at compile time (HLO cost analysis over the "
            "lowered computation — tracing only, never a second "
            "compile), predict step cost from the profiles with a "
            "per-executable EWMA calibration learned from the flight "
            "recorder's measured step times, account live device "
            "bytes in the HBM ledger, and compute per-phase MFU / "
            "HBM-bandwidth roofline gauges.  0 = fully disarmed: one "
            "`is None` check per step, no profiles extracted, "
            "bit-exact serving.  Engines constructed with an explicit "
            "cost_model= ignore the flag")
define_flag("sched_cost_admission", False,
            "cost-model admission gate (observability.costmodel."
            "CostModel.admission_ok): DecodeEngine._admit_one "
            "additionally refuses a bind while the predicted step "
            "cost exceeds the tightest declared slo_tpot_ms among the "
            "candidate and the running set — admit against a latency "
            "budget instead of a slot count.  Default 0 = bit-exact "
            "historical admission; requires FLAGS_cost_model")
define_flag("peak_flops", 0.0,
            "roofline compute ceiling in FLOP/s for the cost "
            "observatory's MFU gauges (paddle_phase_mfu) and step-"
            "cost predictor; 0 (default) = autodetect from the device "
            "kind (datasheet table in observability.costmodel; CPU "
            "pins fixed test values so CI gauges are deterministic)")
define_flag("peak_hbm_gbps", 0.0,
            "roofline memory-bandwidth ceiling in GB/s for the cost "
            "observatory's paddle_phase_hbm_util gauges and step-cost "
            "predictor; 0 (default) = autodetect from the device kind "
            "(CPU pins fixed test values)")
define_flag("peak_ici_gbps", 0.0,
            "roofline interconnect ceiling in GB/s for the cost "
            "observatory's collective-bytes term (sharded executables "
            "under FLAGS_serve_mesh): predict_step_cost adds "
            "collective_bytes / ici_bytes_per_s to the roofline "
            "seconds of any profile whose HLO contains collectives; "
            "0 (default) = autodetect from the device kind (CPU pins "
            "a fixed test value so CI gauges are deterministic)")
define_flag("cost_memory_analysis", False,
            "additionally compile the lowered computation AOT and "
            "record each executable's peak temp-buffer allocation "
            "(Compiled.memory_analysis) into its cost profile and the "
            "HBM ledger's temp_scratch category — one EXTRA XLA "
            "compile per unique executable, so default off")
define_flag("cost_ledger_interval_steps", 128,
            "engine steps between HBM-ledger audits "
            "(observability.costmodel.CostModel.hbm_ledger: attribute "
            "every live device byte to weights / kv_pages / kv_scales "
            "/ draft_pool / misc and surface the unattributed residue "
            "as paddle_hbm_ledger_unattributed_bytes); the audit "
            "walks jax.live_arrays() — cost scales with the process's "
            "live-array count — so it is periodic rather than "
            "per-step (128 steps is still sub-second against any "
            "scrape interval).  <= 0 = audit only on demand "
            "(statusz / telemetry dump)")
define_flag("ops_port", 0,
            "ops-plane HTTP endpoint (observability.opsserver): a "
            "stdlib ThreadingHTTPServer daemon thread serving "
            "/metrics (Prometheus text), /statusz (JSON, ?format="
            "text), /flightz (flight window, ?request=<id> timeline), "
            "/healthz + /readyz (the fleet router's routing key: "
            "live AND capacity headroom > 0 AND no page-severity "
            "alert firing AND no watchdog-overdue step), and /alertz "
            "(declarative alert states + transitions).  Arms the "
            "between-steps alert engine (observability.alerts) on "
            "every DecodeEngine constructed while set.  0 (default) "
            "= fully off: zero listening sockets, zero alert "
            "counters, bit-exact serving; -1 = alert engine armed "
            "WITHOUT the HTTP listener (in-process /alertz state "
            "only).  Ports bind all interfaces — the endpoint is "
            "read-only introspection")
define_flag("alert_interval_steps", 32,
            "engine steps between alert-engine evaluations "
            "(observability.alerts.AlertEngine): each evaluation "
            "samples ~a dozen gauges and walks the rule table on the "
            "engine thread BETWEEN steps — no new hot-path locks, so "
            "the cadence is the only cost knob.  Evaluation also "
            "fires unconditionally on a fatal step fault and at "
            "watchdog abandonment so the crash dump records the "
            "alerts firing at death.  <= 0 falls back to 32")
define_flag("profile", False,
            "profiling plane (observability.profiling): sampled "
            "device-sync probes split each probed step's wall into "
            "device seconds vs host overhead (the engine blocks on "
            "the dispatched executable's output), MEASURED "
            "per-executable MFU lands beside the cost observatory's "
            "roofline gauges with a predicted-vs-measured drift "
            "gauge, compile-time profiles grow a top-K per-op "
            "FLOP/byte table, and bounded capture sessions "
            "(profiling.request_capture) record probe spans on a "
            "'device' chrome-trace track.  The device/host split, "
            "measured MFU and drift ride the flight record, so they "
            "need FLAGS_flight_window > 0 (the default); with the "
            "recorder off, probes still feed the device-seconds "
            "table and capture spans.  0 (default) = fully disarmed: "
            "one `is None` check per step hook, zero probes, zero "
            "new executables, bit-exact serving.  Engines "
            "constructed with an explicit profile= ignore the flag")
define_flag("profile_sample_steps", 64,
            "engine steps between device-sync probes while "
            "FLAGS_profile is armed (every step during an armed "
            "capture session): each probe blocks the engine thread on "
            "the step executable's output, trading one pipeline "
            "bubble for a measured device-vs-host split — sampling "
            "keeps the amortized cost negligible.  <= 1 probes every "
            "step (the bench attribution mode)")
define_flag("profile_dir", "",
            "directory for capture-session device traces: while set, "
            "profiling.request_capture additionally wraps the capture "
            "window in jax.profiler.start_trace/stop_trace so the "
            "XLA-level timeline lands beside the probe spans.  Empty "
            "(default) = probe spans only (the merged chrome trace's "
            "'device' track still works)")
define_flag("fleet_trace", False,
            "fleet-scope distributed tracing (observability."
            "fleettrace): FleetRouter.submit mints a trace id that "
            "rides every /v1/generate, /v1/adopt and /v1/resume leg "
            "as an x-paddle-trace header, the edge threads it into "
            "the frontend so engine-side request spans and flight "
            "records carry it, a failover leg reuses the donor's id "
            "(two segments of one trace), routing / SSE-delivery / "
            "failover decisions become spans on router+edge tracks, "
            "each edge serves /tracez/spans, and the router's "
            "/fleetz rollup merges replica span sets into one "
            "clock-offset-corrected chrome trace.  False (default) "
            "= fully off: zero new wire headers, zero new spans, "
            "zero extra probes, bit-exact serving")
define_flag("use_rbg_rng", True,
            "on TPU, use the hardware RBG PRNG for the framework's random "
            "ops instead of threefry (measured: recovers ~60% of dropout's "
            "train-step cost on ViT-B/16; draws differ from CPU/threefry "
            "runs). Read once at the first key creation — set it via env "
            "or set_flags before any random op / parameter init")
