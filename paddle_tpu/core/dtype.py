"""Dtype system.

Mirrors the reference's POD dtype enum (reference framework.proto:106-141:
BOOL, INT16, INT32, INT64, FP16, FP32, FP64, UINT8, INT8, BF16, COMPLEX64,
COMPLEX128) but maps every dtype onto a canonical ``jnp.dtype``.  On TPU the
preferred compute type is bfloat16; float32 remains the default parameter
dtype, as in the reference (`python/paddle/fluid/framework.py` default dtype
handling).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical names -> jnp dtypes
_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

# Aliases used across the reference python API.
_ALIASES = {
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
    "bf16": "bfloat16",
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
}

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_DEFAULT_DTYPE = [jnp.float32]


def set_default_dtype(d):
    _DEFAULT_DTYPE[0] = convert_dtype(d)


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np dtype, jnp dtype) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _NAME_TO_DTYPE:
            raise ValueError(f"unsupported dtype string: {dtype!r}")
        return jnp.dtype(_NAME_TO_DTYPE[name])
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    return str(jnp.dtype(dtype))


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.complexfloating)
