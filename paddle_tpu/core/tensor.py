"""Tensor: an imperative handle over a `jax.Array`.

Reference: dygraph `VarBase` (`paddle/fluid/imperative/layer.h:66`) — a named,
grad-tracking variable holding a LoDTensor.  Here the payload is a
`jax.Array` (device-resident, XLA-managed); autograd linkage is recorded on
the process tape (see core/tape.py) rather than per-variable GradOpNodes.

Paddle semantics preserved:
* ``stop_gradient`` defaults to True; parameters set it False
  (`python/paddle/fluid/framework.py` Variable.stop_gradient).
* ``.backward()`` / ``.grad`` / ``clear_grad``.
* numpy() / item() / astype / reshape / transpose / indexing.
Most op methods are attached by ``paddle_tpu.ops`` at import time (the
reference attaches these via `varbase_patch_methods.py` monkey patching).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from . import framework


import itertools

_UID = itertools.count(1)


class Tensor:
    # let Tensor win against np arrays in binary ops
    __array_priority__ = 100

    # 'regularizer' lives here (not on Parameter) so plain tensors promoted
    # to trainable leaves can carry one too; Parameter must not redeclare it.
    __slots__ = ("_array", "stop_gradient", "grad", "name", "trainable",
                 "persistable", "regularizer", "_uid", "__weakref__")

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        self._uid = next(_UID)
        if isinstance(data, Tensor):
            data = data._array
        dt = dtype_mod.convert_dtype(dtype) if dtype is not None else None
        if isinstance(data, (jax.Array, jax.core.Tracer)):
            arr = data.astype(dt) if dt is not None and data.dtype != dt else data
        else:
            npdata = np.asarray(data)
            if dt is None and npdata.dtype == np.float64:
                dt = dtype_mod.get_default_dtype()
            arr = jnp.asarray(npdata, dtype=dt)
        self._array = arr
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name
        self.trainable = not stop_gradient
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    @property
    def size(self):
        return int(np.prod(self._array.shape)) if self._array.shape else 1

    @property
    def dtype(self):
        return self._array.dtype

    @property
    def place(self):
        from .place import expected_place

        return expected_place()

    @property
    def T(self):
        from .. import ops

        return ops.transpose(self, list(range(self.ndim))[::-1])

    def numpy(self):
        return np.asarray(self._array)

    def item(self):
        return self._array.item()

    def tolist(self):
        return np.asarray(self._array).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._array.shape[0]

    def __repr__(self):
        grad_s = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}"
            f"{grad_s},\n       {np.asarray(self._array)!r})"
        )

    def __bool__(self):
        return bool(self._array)

    def __int__(self):
        return int(self._array)

    def __index__(self):
        # lets `range(n_tensor)` work eagerly for size-1 tensors; under a
        # trace the int() of a tracer raises ConcretizationTypeError,
        # which jit.to_static catches to trigger the dy2static AST
        # fallback
        if self._array.size != 1:
            raise TypeError("only size-1 tensors convert to an index")
        return int(self._array.reshape(()))

    def __float__(self):
        return float(self._array)

    def __hash__(self):
        return id(self)

    def __deepcopy__(self, memo):
        # a deep copy must get a FRESH uid: the autograd tape keys cotangents
        # by uid, so a copied parameter sharing its source's uid would absorb
        # or lose the source's gradients (e.g. copy.deepcopy of encoder
        # layers in TransformerEncoder)
        import copy as _copy

        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        new._uid = next(_UID)
        new._array = self._array  # jax arrays are immutable; share
        new.stop_gradient = self.stop_gradient
        new.grad = None
        new.name = self.name
        new.trainable = self.trainable
        new.persistable = self.persistable
        for slot in ("optimize_attr", "regularizer", "is_bias", "mesh_axes"):
            if hasattr(self, slot):
                setattr(new, slot, _copy.deepcopy(getattr(self, slot), memo))
        return new

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from . import tape

        tape.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._array, stop_gradient=True, name=self.name)
        return t

    def clone(self):
        from .. import ops

        return ops.assign(self)

    # in-place value replacement (reference: VarBase set_value / share_data)
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._array
        arr = jnp.asarray(value, dtype=self._array.dtype)
        if tuple(arr.shape) != tuple(self._array.shape):
            raise ValueError(
                f"set_value shape mismatch {arr.shape} vs {self._array.shape}"
            )
        # under a jit trace, record the write instead of storing a tracer
        # (it becomes an explicit output of the compiled program)
        if framework.in_trace() and framework.record_trace_write(self, arr):
            return
        self._array = arr

    def copy_(self, other):
        self.set_value(other)
        return self

    # -- conversion ---------------------------------------------------------
    def astype(self, dtype):
        from .. import ops

        return ops.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def cpu(self):
        return self

    def to(self, *args, **kwargs):
        return self

    def __array__(self, dtype=None):
        a = np.asarray(self._array)
        return a.astype(dtype) if dtype is not None else a

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        from .dispatch import dispatch

        if isinstance(idx, Tensor):
            if jnp.issubdtype(idx._array.dtype, jnp.integer):
                # integer gather: feed the index as a (nondiff) operand so
                # the lookup hits the dispatch executable cache — closing
                # over the live array would bypass it on every call.  Bool
                # masks stay closed over (data-dependent output shape
                # cannot be jitted and must run eagerly).
                return dispatch(lambda a, i: a[i], self, idx, nondiff=(1,))
            idx = idx._array
        elif isinstance(idx, tuple):
            idx = tuple(i._array if isinstance(i, Tensor) else i for i in idx)
        return dispatch(lambda a: a[idx], self)

    def __setitem__(self, idx, value):
        if isinstance(idx, Tensor):
            idx = idx._array
        elif isinstance(idx, tuple):
            idx = tuple(i._array if isinstance(i, Tensor) else i for i in idx)
        v = value._array if isinstance(value, Tensor) else value
        new = self._array.at[idx].set(v)
        # route through the same trace-write machinery as set_value so a
        # `t[idx] = x` inside a jit trace becomes a program output instead of
        # leaking a tracer; in eager mode this is an in-place update that
        # (like the reference's inplace ops) detaches prior autograd history.
        if framework.in_trace() and framework.record_trace_write(self, new):
            return
        self._array = new


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent (`python/paddle/tensor/creation.py`)."""
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def unwrap(x):
    return x._array if isinstance(x, Tensor) else x


def wrap(arr, stop_gradient=True):
    return Tensor(arr, stop_gradient=stop_gradient)
