"""Eager autograd tape.

Reference: the dygraph autograd engine builds a `GradOpNode` DAG during
forward (`imperative/tracer.cc:231` CreateGradOpNode) and executes it in
reverse with dependency counting (`imperative/basic_engine.cc:39,235,305`),
merging duplicate gradients through `GradientAccumulator`.

TPU-native design: each eager op records one `TapeNode` holding the `jax.vjp`
pullback of its (pure jnp) compute function.  `backward()` walks the recorded
nodes in reverse execution order, pushing cotangents from output uids to
input tensors; leaves with ``stop_gradient=False`` receive their accumulated
cotangent as ``.grad``.  No per-node scheduling machinery is needed — the
tape is already a topological order.

Lifetime: nodes hold inputs strongly (they are needed to chain/accumulate)
but outputs only weakly, keyed by a monotonically increasing tensor uid (so
CPython id reuse cannot corrupt the walk).  When every output of a node has
died, no live root can reach it, so a periodic sweep drops it — this plays
the role of the reference's shared_ptr graph ownership, where dropping the
last VarBase frees its GradOpNode; without it a forward-only loop that
forgets `no_grad` would pin every activation.
"""
from __future__ import annotations

import weakref
from typing import Any, Callable, List, Optional


class TapeNode:
    __slots__ = (
        "vjp_fn",
        "input_refs",
        "output_wrefs",
        "output_uids",
        "_out_protos",
        "out_is_tuple",
        "released",
    )

    def __init__(self, vjp_fn, inputs, outputs, out_is_tuple=False):
        self.vjp_fn = vjp_fn
        self.input_refs = inputs
        self.output_wrefs = [weakref.ref(t) for t in outputs]
        self.output_uids = [t._uid for t in outputs]
        self._out_protos = [(t._array.shape, t._array.dtype) for t in outputs]
        self.out_is_tuple = out_is_tuple
        self.released = False

    def dead(self) -> bool:
        return self.released or all(r() is None for r in self.output_wrefs)


_SWEEP_INTERVAL = 256


class Tape:
    def __init__(self):
        self.nodes: List[TapeNode] = []
        self._since_sweep = 0

    def record(self, node: TapeNode):
        self.nodes.append(node)
        self._since_sweep += 1
        if self._since_sweep >= _SWEEP_INTERVAL:
            self.sweep()

    def sweep(self):
        """Drop nodes unreachable from any live tensor (all outputs died)."""
        self._since_sweep = 0
        # iterate until fixpoint is unnecessary in one pass per sweep: dropping
        # a node releases its input refs, which may kill upstream outputs —
        # they get collected on the next sweep.
        self.nodes = [n for n in self.nodes if not n.dead()]

    def clear(self):
        self.nodes.clear()
        self._since_sweep = 0


_TAPE = Tape()


def default_tape() -> Tape:
    return _TAPE


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run reverse-mode over the recorded tape from `tensors` roots."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .tensor import Tensor

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    if not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangent accumulator keyed by tensor uid
    cot = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_arr = jnp.ones_like(t._array)
        else:
            g_arr = g._array if isinstance(g, Tensor) else jnp.asarray(g)
        cot[t._uid] = cot.get(t._uid, 0) + g_arr

    tape = default_tape()
    for node in reversed(tape.nodes):
        if node.released:
            continue
        out_cots = [cot.get(uid) for uid in node.output_uids]
        if all(c is None for c in out_cots):
            continue
        full = []
        for c, proto in zip(out_cots, node._out_protos):
            if not jnp.issubdtype(proto[1], jnp.inexact):
                # integer/bool outputs (e.g. valid counts, argmax indices)
                # take float0 cotangents per jax.vjp's contract
                full.append(np.zeros(proto[0], jax.dtypes.float0))
                continue
            c = c if c is not None else jnp.zeros(proto[0], proto[1])
            if hasattr(c, "dtype") and c.dtype != proto[1]:
                c = c.astype(proto[1])
            full.append(c)
        in_cots = node.vjp_fn(tuple(full) if node.out_is_tuple else full[0])
        for t, g in zip(node.input_refs, in_cots):
            if g is None:
                continue
            cot[t._uid] = cot.get(t._uid, 0) + g
        if not retain_graph:
            node.released = True
            node.vjp_fn = None

    # deposit grads once per distinct tensor (GradientAccumulator role)
    seen = set()
    for node in tape.nodes:
        for t in node.input_refs:
            if t._uid not in seen:
                seen.add(t._uid)
                _maybe_set_grad(t, cot)
    for t in tensors:
        if t._uid not in seen:
            seen.add(t._uid)
            _maybe_set_grad(t, cot)

    if not retain_graph:
        tape.clear()


def _maybe_set_grad(t, cot):
    from .tensor import Tensor

    g = cot.get(t._uid)
    if g is None or t.stop_gradient:
        return
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._array + g, stop_gradient=True)
