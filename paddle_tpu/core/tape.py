"""Eager autograd tape.

Reference: the dygraph autograd engine builds a `GradOpNode` DAG during
forward (`imperative/tracer.cc:231` CreateGradOpNode) and executes it in
reverse with dependency counting (`imperative/basic_engine.cc:39,235,305`),
merging duplicate gradients through `GradientAccumulator`.

TPU-native design: each eager op records one `TapeNode` holding the `jax.vjp`
pullback of its (pure jnp) compute function.  `backward()` walks the recorded
nodes in reverse execution order, pushing cotangents from output uids to
input tensors; leaves with ``stop_gradient=False`` receive their accumulated
cotangent as ``.grad``.  No per-node scheduling machinery is needed — the
tape is already a topological order.

Lifetime: nodes hold inputs strongly (they are needed to chain/accumulate)
but outputs only weakly, keyed by a monotonically increasing tensor uid (so
CPython id reuse cannot corrupt the walk).  When every output of a node has
died, no live root can reach it, so a periodic sweep drops it — this plays
the role of the reference's shared_ptr graph ownership, where dropping the
last VarBase frees its GradOpNode; without it a forward-only loop that
forgets `no_grad` would pin every activation.
"""
from __future__ import annotations

import weakref
from typing import Any, Callable, List, Optional


class TapeNode:
    __slots__ = (
        "vjp_fn",
        "primal_fn",
        "input_refs",
        "output_wrefs",
        "output_uids",
        "_out_protos",
        "out_is_tuple",
        "released",
    )

    def __init__(self, vjp_fn, inputs, outputs, out_is_tuple=False,
                 primal_fn=None):
        self.vjp_fn = vjp_fn
        # pure function of the differentiable inputs; kept so backward can
        # itself be re-derived under dispatch (paddle.grad(create_graph=True)
        # — reference PartialGradEngine double-grad)
        self.primal_fn = primal_fn
        self.input_refs = inputs
        self.output_wrefs = [weakref.ref(t) for t in outputs]
        self.output_uids = [t._uid for t in outputs]
        self._out_protos = [(t._array.shape, t._array.dtype) for t in outputs]
        self.out_is_tuple = out_is_tuple
        self.released = False

    def dead(self) -> bool:
        return self.released or all(r() is None for r in self.output_wrefs)


_SWEEP_INTERVAL = 256


class Tape:
    def __init__(self):
        self.nodes: List[TapeNode] = []
        self._since_sweep = 0

    def record(self, node: TapeNode):
        self.nodes.append(node)
        self._since_sweep += 1
        if self._since_sweep >= _SWEEP_INTERVAL:
            self.sweep()

    def sweep(self):
        """Drop nodes unreachable from any live tensor (all outputs died)."""
        self._since_sweep = 0
        # iterate until fixpoint is unnecessary in one pass per sweep: dropping
        # a node releases its input refs, which may kill upstream outputs —
        # they get collected on the next sweep.
        self.nodes = [n for n in self.nodes if not n.dead()]

    def clear(self):
        self.nodes.clear()
        self._since_sweep = 0


_TAPE = Tape()


def default_tape() -> Tape:
    return _TAPE


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False, touched=None):
    """Run reverse-mode over the recorded tape from `tensors` roots.
    create_graph=True records the backward computation itself on the tape
    (double-grad; reference `imperative/partial_grad_engine.cc`)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .tensor import Tensor

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    if not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    if create_graph:
        return _backward_create_graph(list(tensors), list(grad_tensors),
                                      touched)

    # cotangent accumulator keyed by tensor uid
    cot = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_arr = jnp.ones_like(t._array)
        else:
            g_arr = g._array if isinstance(g, Tensor) else jnp.asarray(g)
        cot[t._uid] = cot.get(t._uid, 0) + g_arr

    tape = default_tape()
    for node in reversed(tape.nodes):
        if node.released:
            continue
        out_cots = [cot.get(uid) for uid in node.output_uids]
        if all(c is None for c in out_cots):
            continue
        full = []
        for c, proto in zip(out_cots, node._out_protos):
            if not jnp.issubdtype(proto[1], jnp.inexact):
                # integer/bool outputs (e.g. valid counts, argmax indices)
                # take float0 cotangents per jax.vjp's contract
                full.append(np.zeros(proto[0], jax.dtypes.float0))
                continue
            c = c if c is not None else jnp.zeros(proto[0], proto[1])
            if hasattr(c, "dtype") and c.dtype != proto[1]:
                c = c.astype(proto[1])
            full.append(c)
        in_cots = node.vjp_fn(tuple(full) if node.out_is_tuple else full[0])
        for t, g in zip(node.input_refs, in_cots):
            if g is None:
                continue
            cot[t._uid] = cot.get(t._uid, 0) + g
        if not retain_graph:
            node.released = True
            # drop both callables: the vjp (cached path: a _CachedVjp
            # pinning the call's operand arrays) and the primal closure
            # (which pins the same arrays for double-grad replay) — a
            # released node must not keep activations alive
            node.vjp_fn = None
            node.primal_fn = None

    # deposit grads once per distinct tensor (GradientAccumulator role)
    seen = set()
    for node in tape.nodes:
        for t in node.input_refs:
            if t._uid not in seen:
                seen.add(t._uid)
                _maybe_set_grad(t, cot, touched)
    for t in tensors:
        if t._uid not in seen:
            seen.add(t._uid)
            _maybe_set_grad(t, cot, touched)

    if not retain_graph:
        tape.clear()


def _maybe_set_grad(t, cot, touched=None):
    from .tensor import Tensor

    g = cot.get(t._uid)
    if g is None or t.stop_gradient:
        return
    if touched is not None:
        # caller (paddle.grad) restores these afterwards — record exactly
        # the tensors written, at write time (no O(tape) pre-scan)
        touched.append((t, t.grad))
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._array + g, stop_gradient=True)


def _backward_create_graph(tensors, grad_tensors, touched=None):
    """Differentiable backward: replays each node's vjp THROUGH dispatch so
    the gradient computation is itself taped.  The graph is retained (the
    reference's create_graph contract implies retain_graph)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .dispatch import dispatch
    from .tensor import Tensor

    cot = {}  # uid -> Tensor (taped)
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            gt = Tensor(jnp.ones_like(t._array))
        else:
            gt = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g))
        prev = cot.get(t._uid)
        cot[t._uid] = gt if prev is None else prev + gt

    tape = default_tape()
    # snapshot: the second-order dispatches below append NEW nodes
    nodes = list(tape.nodes)
    for node in reversed(nodes):
        if node.released:
            continue
        out_cots = [cot.get(uid) for uid in node.output_uids]
        if all(c is None for c in out_cots):
            continue
        if node.primal_fn is None:
            raise RuntimeError(
                "create_graph=True needs the primal function; this node "
                "(custom PyLayer?) recorded only an opaque vjp")
        protos = node._out_protos
        inexact = tuple(i for i, p in enumerate(protos)
                        if jnp.issubdtype(p[1], jnp.inexact))
        cot_args = []
        for i in inexact:
            c = out_cots[i]
            cot_args.append(c if c is not None
                            else Tensor(jnp.zeros(protos[i][0], protos[i][1])))
        n_in = len(node.input_refs)

        def second(*args, _pf=node.primal_fn, _n=n_in, _protos=protos,
                   _inexact=inexact, _tup=node.out_is_tuple):
            primals = args[:_n]
            cots = list(args[_n:])
            full = []
            k = 0
            for i, p in enumerate(_protos):
                if i in _inexact:
                    c = cots[k]
                    if c.dtype != p[1]:
                        c = c.astype(p[1])
                    full.append(c)
                    k += 1
                else:
                    full.append(np.zeros(p[0], jax.dtypes.float0))
            _, vjp = jax.vjp(_pf, *primals)
            return tuple(vjp(tuple(full) if _tup else full[0]))

        in_cots = dispatch(second, *node.input_refs, *cot_args)
        if not isinstance(in_cots, tuple):
            in_cots = (in_cots,)
        for t, g in zip(node.input_refs, in_cots):
            prev = cot.get(t._uid)
            cot[t._uid] = g if prev is None else prev + g

    # deposit differentiable grads (further backward can flow through them)
    seen = set()
    for node in nodes:
        for t in node.input_refs:
            if t._uid not in seen:
                seen.add(t._uid)
                _deposit_graph_grad(t, cot, touched)
    for t in tensors:
        if t._uid not in seen:
            seen.add(t._uid)
            _deposit_graph_grad(t, cot, touched)


def _deposit_graph_grad(t, cot, touched=None):
    g = cot.get(t._uid)
    if g is None or t.stop_gradient:
        return
    if touched is not None:
        touched.append((t, t.grad))
    t.grad = g if t.grad is None else t.grad + g
