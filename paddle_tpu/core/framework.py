"""Process-global framework state: grad mode, trace mode, RNG.

The reference keeps equivalent state in `imperative::Tracer` (has_grad flag,
`imperative/tracer.cc:144`) and the dygraph/static mode switch in
`python/paddle/fluid/framework.py`.  Here there are two orthogonal modes:

* **grad mode** — whether eager ops record onto the autograd tape
  (`no_grad` disables, like `tracer.has_grad=False`).
* **trace mode** — set while a `to_static`/jit trace is being captured.  In
  trace mode ops do NOT build the eager tape (gradients come from `jax.grad`
  over the captured pure function) and randomness draws from an explicitly
  threaded key so the captured program is a pure function.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np

from . import flags as _flags


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.trace_mode = False
        self.trace_rng_key = None  # threaded PRNG key during jit tracing
        # buffer mutations captured during a trace (id(tensor) -> traced array)
        # so that e.g. BatchNorm running-stat updates become explicit outputs
        # of the compiled program instead of leaking tracers (reference:
        # batch_norm_op writes MeanOut/VarianceOut in-kernel).
        self.trace_writes = None
        self.amp_enabled = False
        self.amp_dtype = None
        self.amp_level = "O1"


_state = _State()


def grad_enabled() -> bool:
    return _state.grad_enabled and not _state.trace_mode


def in_trace() -> bool:
    return _state.trace_mode


@contextlib.contextmanager
def no_grad_guard():
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad_guard():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def trace_guard(rng_key=None, writes=None):
    prev = (_state.trace_mode, _state.trace_rng_key, _state.trace_writes)
    _state.trace_mode = True
    _state.trace_rng_key = rng_key
    _state.trace_writes = writes if writes is not None else {}
    try:
        yield
    finally:
        _state.trace_mode, _state.trace_rng_key, _state.trace_writes = prev


def record_trace_write(tensor, array):
    if _state.trace_writes is not None:
        _state.trace_writes[id(tensor)] = array
        return True
    return False


def get_trace_write(tensor):
    if _state.trace_writes is not None:
        return _state.trace_writes.get(id(tensor))
    return None


# ---------------------------------------------------------------------------
# RNG.  Eager mode: a stateful splitting generator (paddle.seed semantics).
# Trace mode: keys are split off the threaded trace key so that the captured
# program stays pure (a fresh key is fed per invocation by the jit wrapper).
# ---------------------------------------------------------------------------
_RNG_IMPL = None


def _rng_impl() -> str:
    """Framework PRNG impl, decided once at first key creation (NOT at
    import — probing the backend at import would force JAX backend init as
    a side effect of `import paddle_tpu`): the hardware RBG generator on
    TPU (threefry mask generation measurably slows dropout-bearing train
    steps — ViT-B/16 630 -> 719 imgs/s switching to rbg, round-3 probe),
    threefry elsewhere.  Only paddle_tpu's own keys are affected; the
    process-global jax default impl is never touched."""
    global _RNG_IMPL
    if _RNG_IMPL is None:
        impl = "threefry2x32"
        try:
            if _flags.flag("use_rbg_rng") and jax.default_backend() == "tpu":
                impl = "rbg"
        except Exception:
            pass
        _RNG_IMPL = impl
    return _RNG_IMPL


def make_rng_key(seed: int = 0):
    """Typed PRNG key with the framework's impl (see `_rng_impl`).  All
    key-creation sites that feed the jit trace machinery must use this so
    trace-time and run-time keys agree in impl and shape."""
    return jax.random.key(int(seed), impl=_rng_impl())


class Generator:
    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key = None  # created lazily via make_rng_key

    def seed(self, seed: int):
        self._seed = int(seed)
        self._key = None

    def next_key(self):
        if _state.trace_mode:
            if _state.trace_rng_key is None:
                raise RuntimeError(
                    "random op inside a jit trace but no rng key was threaded; "
                    "call the compiled function through paddle_tpu.jit"
                )
            _state.trace_rng_key, sub = jax.random.split(_state.trace_rng_key)
            return sub
        if self._key is None:
            self._key = make_rng_key(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub


default_generator = Generator(np.random.SeedSequence().entropy % (2**31))


def seed(s: int):
    default_generator.seed(int(s))
    return default_generator


def get_rng_key():
    return default_generator.next_key()


# AMP state accessors (used by core.dispatch autocast and paddle_tpu.amp)
def amp_state():
    return _state


def amp_sig():
    """(enabled, compute_dtype) pair for dispatch cache keying: the
    autocast white/black-list pass is folded into the cached traced
    computation (core/dispatch.py), so the AMP state must be part of the
    executable cache key rather than a per-call Python pass."""
    return _state.amp_enabled, _state.amp_dtype
