"""Device identity ("Place") system.

Reference: `paddle/fluid/platform/place.h:26-150` defines CPUPlace / CUDAPlace
/ XPUPlace / NPUPlace as a tagged union.  Here the accelerator is the TPU and
device handles are `jax.Device` objects; a Place is a thin named handle that
resolves to one.  Unlike the reference there is no per-place kernel registry —
placement is expressed to XLA via shardings / `jax.device_put`.
"""
from __future__ import annotations

import functools

import jax


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return (
            type(self) is type(other) and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if d.platform == self.device_type]
        if not devs:
            # fall back to the default backend (e.g. running TPU code paths
            # on the CPU simulator mesh)
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "tpu"


# Alias kept so reference-era code written against CUDAPlace keeps running:
# the accelerator place in this framework is the TPU.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace


_EXPECTED_PLACE = [None]


@functools.lru_cache(maxsize=None)
def _default_place() -> Place:
    platforms = {d.platform for d in jax.devices()}
    if "tpu" in platforms:
        return TPUPlace(0)
    return CPUPlace(0)


def set_device(device) -> Place:
    """paddle.set_device equivalent: 'cpu', 'tpu', 'tpu:0', Place."""
    if isinstance(device, Place):
        _EXPECTED_PLACE[0] = device
        return device
    name, _, idx = str(device).partition(":")
    idx = int(idx) if idx else 0
    cls = {"cpu": CPUPlace, "tpu": TPUPlace, "gpu": TPUPlace, "xpu": TPUPlace}.get(
        name
    )
    if cls is None:
        raise ValueError(f"unknown device {device!r}")
    _EXPECTED_PLACE[0] = cls(idx)
    return _EXPECTED_PLACE[0]


def get_device() -> str:
    p = _EXPECTED_PLACE[0] or _default_place()
    return f"{p.device_type}:{p.device_id}"


def expected_place() -> Place:
    return _EXPECTED_PLACE[0] or _default_place()


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())
