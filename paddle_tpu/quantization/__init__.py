"""Model quantization (QAT + PTQ).

Reference: `python/paddle/fluid/contrib/slim/quantization/` (43 files) —
fake-quant operators (`operators/fake_quantize_op.*`: abs_max,
moving_average_abs_max, channel_wise_abs_max, the *_dequantize fused
variants), `ImperativeQuantAware` (imperative/qat.py) which swaps
Linear/Conv2D for quantized twins, and post-training quantization
(`post_training_quantization.py`).

TPU-native: fake-quant is a pure jnp quantize-dequantize with a
straight-through-estimator gradient (``x + stop_grad(q(x) - x)``), which
XLA fuses into adjacent ops — the reference's separate CUDA kernels and
the scale/ZeroPoint attribute plumbing collapse into this one pattern.
int8 deployment on TPU targets the MXU's int8 path via XLA's native
quantized dot when the saved model is lowered.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap
from ..nn.layer.layers import Layer

__all__ = [
    "fake_quantize_abs_max", "fake_quantize_channel_wise_abs_max",
    "fake_quantize_moving_average_abs_max", "QuantizedLinear",
    "QuantizedConv2D", "ImperativeQuantAware", "ImperativePTQ",
]


# ---------------------------------------------------------------------------
# fake-quant primitives (quantize-dequantize with STE)
# ---------------------------------------------------------------------------
def _qdq(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    # straight-through estimator: forward q, backward identity
    return x + jax.lax.stop_gradient(q - x)


def fake_quantize_abs_max(x, bit_length=8):
    """reference `fake_quantize_abs_max` (`operators/fake_quantize_op.cc`):
    scale = max|x| over the whole tensor."""
    def f(a):
        return _qdq(a, jnp.max(jnp.abs(a)), bit_length)

    return dispatch(f, x)


def dequantize_abs_max(x, scale, max_range, name=None):
    """reference `dequantize_abs_max` (`operators/dequantize_abs_max_op.cc`):
    out = x * scale / max_range (int8 -> float recovery)."""
    def f(a, s):
        return a.astype(jnp.float32) * s / max_range

    return dispatch(f, x, scale)


def dequantize_log(x, dict_table, name=None):
    """reference `dequantize_log` (`operators/dequantize_log_op.cc`):
    log-quantized uint8 codes -> float via a 128-entry lookup table;
    codes >= 128 map to the negative of entry code-128."""
    def f(a, table):
        code = a.astype(jnp.int32)
        neg = code >= 128
        idx = jnp.where(neg, code - 128, code)
        val = table[jnp.clip(idx, 0, table.shape[0] - 1)]
        return jnp.where(neg, -val, val)

    return dispatch(f, x, dict_table, nondiff=(0,))


def moving_average_abs_max_scale(x, state=None, accum=None,
                                 moving_rate=0.9, name=None):
    """reference `moving_average_abs_max_scale`
    (`operators/fake_quantize_op.cc`): running |x|_max scale tracker —
    state = rate*state + 1; accum = rate*accum + max|x|;
    scale = accum/state.  Returns (x, scale, new_state, new_accum)."""
    from ..core.tensor import unwrap

    st = unwrap(state) if state is not None else jnp.ones((), jnp.float32)
    ac = unwrap(accum) if accum is not None else jnp.zeros((), jnp.float32)

    def f(a, s, c):
        new_s = moving_rate * s + 1.0
        new_c = moving_rate * c + jnp.max(jnp.abs(a))
        return a, new_c / new_s, new_s, new_c

    return dispatch(f, x, Tensor(st), Tensor(ac), nondiff=(1, 2))


def fake_quantize_channel_wise_abs_max(x, bit_length=8, quant_axis=0):
    """reference `fake_channel_wise_quantize_abs_max`: per-output-channel
    scales (weights)."""
    def f(a):
        axes = tuple(i for i in range(a.ndim) if i != quant_axis)
        scale = jnp.max(jnp.abs(a), axis=axes, keepdims=True)
        return _qdq(a, scale, bit_length)

    return dispatch(f, x)


def fake_quantize_moving_average_abs_max(x, state, bit_length=8, rate=0.9,
                                         update=True):
    """reference `fake_quantize_moving_average_abs_max`: activation scale is
    an EMA of batch abs-max.  `state` is a scalar Tensor buffer; returns
    (quantized, new_state).

    update=False (eval/deploy) quantizes with the FROZEN stored scale —
    batch content must not change deployed numerics.  rate=None switches
    the update to a running max (PTQ calibration accumulates the max over
    all calibration batches rather than keeping the last one)."""
    def f(a, s):
        cur = jnp.max(jnp.abs(a))
        if not update:
            new_s = jnp.where(s > 0, s, cur)  # frozen; cur only if never set
        elif rate is None:
            new_s = jnp.maximum(s, cur)
        else:
            new_s = jnp.where(s > 0, rate * s + (1 - rate) * cur, cur)
        return _qdq(a, new_s, bit_length), new_s

    return dispatch(f, x, state)


# ---------------------------------------------------------------------------
# quantized layer twins
# ---------------------------------------------------------------------------
class QuantizedLinear(Layer):
    """Linear with fake-quant on weights (channel-wise) and activations
    (moving-average), reference `imperative/qat.py QuantizedLinear`."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        if getattr(layer, "bias", None) is not None:
            self.bias = layer.bias
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self.register_buffer("_act_scale", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        xq, new_scale = fake_quantize_moving_average_abs_max(
            x, self._act_scale, self._abits, self._rate,
            update=self.training)
        if self.training:
            from ..core import framework

            if not framework.record_trace_write(self._act_scale,
                                                new_scale._array):
                self._act_scale._array = new_scale._array
        wq = fake_quantize_channel_wise_abs_max(self.weight, self._wbits,
                                                quant_axis=1)
        out = xq.matmul(wq)
        if getattr(self, "bias", None) is not None:
            out = out + self.bias
        return out


class QuantizedConv2D(Layer):
    """Conv2D with fake-quant, reference `imperative/qat.py
    QuantizedConv2D`."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self._inner = layer
        self.weight = layer.weight
        if getattr(layer, "bias", None) is not None:
            self.bias = layer.bias
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self.register_buffer("_act_scale", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        from ..nn import functional as F

        xq, new_scale = fake_quantize_moving_average_abs_max(
            x, self._act_scale, self._abits, self._rate,
            update=self.training)
        if self.training:
            from ..core import framework

            if not framework.record_trace_write(self._act_scale,
                                                new_scale._array):
                self._act_scale._array = new_scale._array
        wq = fake_quantize_channel_wise_abs_max(self.weight, self._wbits,
                                                quant_axis=0)
        inner = self._inner
        return F.conv2d(xq, wq, bias=getattr(self, "bias", None),
                        stride=inner._stride, padding=inner._padding,
                        dilation=inner._dilation, groups=inner._groups,
                        data_format=inner._data_format)


class ImperativeQuantAware:
    """Quantization-aware training entry (reference `imperative/qat.py:81`):
    `quantize(model)` swaps supported layers for quantized twins in place;
    `save_quantized_model(model, path, input_spec)` exports via jit.save."""

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 quantizable_layer_type=("Linear", "Conv2D")):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._types = set(quantizable_layer_type)

    def quantize(self, model: Layer):
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D

        def convert(layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, Linear) and "Linear" in self._types:
                    layer._sub_layers[name] = QuantizedLinear(
                        sub, self._wbits, self._abits, self._rate)
                elif isinstance(sub, Conv2D) and "Conv2D" in self._types:
                    layer._sub_layers[name] = QuantizedConv2D(
                        sub, self._wbits, self._abits, self._rate)
                else:
                    convert(sub)

        convert(model)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit

        jit.save(model, path, input_spec=input_spec)


class ImperativePTQ:
    """Post-training quantization (reference
    `post_training_quantization.py`): run calibration batches to collect
    activation abs-max stats, then freeze the scales into fake-quant
    wrappers."""

    def __init__(self, weight_bits=8, activation_bits=8):
        self._wbits = weight_bits
        self._abits = activation_bits

    def quantize(self, model: Layer, calib_fn=None):
        """`calib_fn(model)` should run representative forward passes."""
        # rate=None -> calibration accumulates the running max over all
        # calibration batches (not just the last one)
        qat = ImperativeQuantAware(self._wbits, self._abits,
                                   moving_rate=None)
        qat.quantize(model)
        if calib_fn is not None:
            model.eval()
            was_training = False
            # temporarily enable scale collection during calibration
            for sub in model.sublayers(include_self=True):
                if isinstance(sub, (QuantizedLinear, QuantizedConv2D)):
                    sub.training = True
            calib_fn(model)
            for sub in model.sublayers(include_self=True):
                if isinstance(sub, (QuantizedLinear, QuantizedConv2D)):
                    sub.training = was_training
        return model


from .int8 import (Int8Conv2D, Int8Linear, convert_to_int8,  # noqa: E402
                   quantize_act, quantize_weight)
