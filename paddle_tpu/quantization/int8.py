"""TRUE int8 execution (round-4 VERDICT #4).

Reference capability: deployed int8 inference —
`inference/api/mkldnn_quantizer.cc:1` (CPU int8 via oneDNN) and the
TensorRT int8 path.  TPU-native redesign: the MXU multiplies s8 x s8 into
s32 natively, so int8 layers run `lax.dot_general` /
`lax.conv_general_dilated` with `preferred_element_type=int32` on int8
operands and dequantize the s32 accumulator with the folded
`act_scale * w_scale / q_max^2` factor — no fake-quant simulation in the
serving path, the arithmetic itself is int8.

Flow: QAT/PTQ (`ImperativeQuantAware`/`ImperativePTQ`) calibrates
activation scales -> `convert_to_int8(model)` materializes int8 weights
+ frozen scales and swaps the fake-quant twins for these executing
layers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, unwrap
from ..nn import Layer

Q_MAX = 127.0


def quantize_weight(w, quant_axis: int):
    """Per-channel symmetric int8: returns (q_w int8, scale f32[channels])."""
    w = unwrap(w)
    red = tuple(i for i in range(w.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(w), axis=red)
    shape = [1] * w.ndim
    shape[quant_axis] = -1
    q = jnp.clip(jnp.round(w / jnp.maximum(scale.reshape(shape), 1e-30)
                           * Q_MAX), -Q_MAX, Q_MAX).astype(jnp.int8)
    return q, scale


def quantize_act(x, scale):
    """Per-tensor symmetric int8 with a calibrated static scale."""
    return jnp.clip(jnp.round(unwrap(x) / jnp.maximum(scale, 1e-30)
                              * Q_MAX), -Q_MAX, Q_MAX).astype(jnp.int8)


class Int8Linear(Layer):
    """y = dequant(s8(x) @ s8(W)) + b — the matmul executes in int8 on
    the MXU (s32 accumulation), per-out-channel weight scales."""

    def __init__(self, weight, bias, act_scale):
        super().__init__()
        qw, wscale = quantize_weight(weight, quant_axis=1)
        self.register_buffer("qweight", Tensor(qw))
        self.register_buffer("w_scale", Tensor(wscale))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(unwrap(act_scale),
                                                jnp.float32).reshape(())))
        if bias is not None:
            self.register_buffer("bias_f32",
                                 Tensor(unwrap(bias).astype(jnp.float32)))
        else:
            self.bias_f32 = None

    def forward(self, x):
        qx = quantize_act(x, self.act_scale._array)
        acc = jax.lax.dot_general(
            qx, self.qweight._array,
            dimension_numbers=(((qx.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        deq = acc.astype(jnp.float32) * (
            self.act_scale._array * self.w_scale._array / (Q_MAX * Q_MAX))
        if self.bias_f32 is not None:
            deq = deq + self.bias_f32._array
        return Tensor(deq.astype(unwrap(x).dtype))


class Int8Conv2D(Layer):
    """conv executes in int8 (s32 accumulation), per-out-channel weight
    scales (quant_axis=0 — OIHW)."""

    def __init__(self, weight, bias, act_scale, stride, padding, dilation,
                 groups, data_format="NCHW"):
        super().__init__()
        qw, wscale = quantize_weight(weight, quant_axis=0)
        self.register_buffer("qweight", Tensor(qw))
        self.register_buffer("w_scale", Tensor(wscale))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(unwrap(act_scale),
                                                jnp.float32).reshape(())))
        if bias is not None:
            self.register_buffer("bias_f32",
                                 Tensor(unwrap(bias).astype(jnp.float32)))
        else:
            self.bias_f32 = None
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format

    def forward(self, x):
        # same stride/padding normalization contract as F.conv2d — the
        # layer this replaces accepted "SAME"/"VALID"/asymmetric pads
        from ..nn.functional.conv import _padding, _pair

        qx = quantize_act(x, self.act_scale._array)
        stride = tuple(_pair(self._stride, 2))
        pad = _padding(self._padding, 2)
        dil = tuple(_pair(self._dilation, 2))
        nhwc = self._data_format not in ("NCHW", "NCL", "NCDHW")
        dn = ("NHWC", "OIHW", "NHWC") if nhwc else \
            ("NCHW", "OIHW", "NCHW")
        ch_shape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
        acc = jax.lax.conv_general_dilated(
            qx, self.qweight._array, window_strides=stride,
            padding=pad, rhs_dilation=dil,
            feature_group_count=max(self._groups, 1),
            dimension_numbers=dn, preferred_element_type=jnp.int32)
        deq = acc.astype(jnp.float32) * (
            self.act_scale._array
            * self.w_scale._array.reshape(ch_shape) / (Q_MAX * Q_MAX))
        if self.bias_f32 is not None:
            deq = deq + self.bias_f32._array.reshape(ch_shape)
        return Tensor(deq.astype(unwrap(x).dtype))


def convert_to_int8(model: Layer) -> Layer:
    """Swap PTQ/QAT fake-quant twins (QuantizedLinear/QuantizedConv2D,
    with calibrated `_act_scale`) for EXECUTING int8 layers in place."""
    from . import QuantizedConv2D, QuantizedLinear

    def _scale_or_raise(sub, name):
        s = float(np.asarray(jax.device_get(sub._act_scale._array)))
        if not s > 0:
            raise ValueError(
                f"convert_to_int8: layer {name!r} has no calibrated "
                "activation scale — run PTQ calibration (ImperativePTQ."
                "quantize(model, calib_fn=...)) or QAT steps first; "
                "converting with scale 0 would saturate every "
                "activation")
        return sub._act_scale._array

    def convert(layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, QuantizedLinear):
                layer._sub_layers[name] = Int8Linear(
                    sub.weight, getattr(sub, "bias", None),
                    _scale_or_raise(sub, name))
            elif isinstance(sub, QuantizedConv2D):
                inner = sub._inner
                layer._sub_layers[name] = Int8Conv2D(
                    sub.weight, getattr(sub, "bias", None),
                    _scale_or_raise(sub, name), inner._stride,
                    inner._padding, inner._dilation, inner._groups,
                    getattr(inner, "_data_format", "NCHW"))
            else:
                convert(sub)

    convert(model)
    return model
