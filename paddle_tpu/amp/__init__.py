"""Automatic mixed precision.

Reference: `python/paddle/amp/auto_cast.py:20` (auto_cast context over the
tracer's AMP white/black lists, `imperative/amp_auto_cast.cc`) and
`amp/grad_scaler.py:20` (dynamic loss scaling via `check_finite_and_unscale`
+ `update_loss_scaling` ops, `operators/amp/`).

TPU-native: the autocast dtype defaults to **bfloat16** — the MXU's native
type — and because bf16 has fp32-range exponents, loss scaling is a no-op
numerically; GradScaler keeps full reference semantics (scale/unscale,
dynamic adjustment, inf/nan skip) for fp16 compatibility and API parity.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core import framework
from ..core.tensor import Tensor


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    st = framework.amp_state()
    prev = (st.amp_enabled, st.amp_dtype, st.amp_level)
    st.amp_enabled = bool(enable)
    st.amp_dtype = dtype_mod.convert_dtype(dtype)
    st.amp_level = level
    try:
        yield
    finally:
        st.amp_enabled, st.amp_dtype, st.amp_level = prev


amp_guard = auto_cast


def is_auto_cast_enabled():
    return framework.amp_state().amp_enabled


class GradScaler:
    """Dynamic loss scaler (reference `amp/grad_scaler.py`, semantics of
    `update_loss_scaling_op`)."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._parameters or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is None:
                continue
            g = p.grad._array * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found = True
            p.grad = Tensor(g)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the compute dtype while optimizers
    keep fp32 master copies (reference `fluid/contrib/mixed_precision/decorator.py`).
    On TPU we keep params fp32 and autocast activations instead (XLA keeps
    the matmuls in bf16); this function exists for API parity and casts
    explicitly when asked."""
    if level == "O2" and models is not None and dtype in ("float16", "bfloat16"):
        pass  # params stay fp32 (master weights); autocast handles compute dtype
    if optimizers is None:
        return models
    return models, optimizers
