"""Probability distributions (reference `python/paddle/distribution.py`:
Distribution, Normal, Uniform, Categorical)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import framework
from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x, jnp.float32))


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        from ..ops import exp

        return exp(self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=(), seed=0):
        key = framework.get_rng_key()
        base_shape = jnp.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape)
        )
        full = tuple(shape) + base_shape
        eps = jax.random.normal(key, full, jnp.float32)
        return Tensor(unwrap(self.loc) + unwrap(self.scale) * eps)

    def rsample(self, shape=()):
        return self.sample(shape)

    def entropy(self):
        return dispatch(
            lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
            self.scale,
        )

    def log_prob(self, value):
        return dispatch(
            lambda v, m, s: -((v - m) ** 2) / (2 * s * s) - jnp.log(s)
            - 0.5 * math.log(2 * math.pi),
            _t(value), self.loc, self.scale,
        )

    def kl_divergence(self, other):
        return dispatch(
            lambda m1, s1, m2, s2: jnp.log(s2 / s1)
            + (s1 * s1 + (m1 - m2) ** 2) / (2 * s2 * s2) - 0.5,
            self.loc, self.scale, other.loc, other.scale,
        )


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape=(), seed=0):
        key = framework.get_rng_key()
        base_shape = jnp.broadcast_shapes(
            tuple(self.low.shape), tuple(self.high.shape)
        )
        full = tuple(shape) + base_shape
        u = jax.random.uniform(key, full, jnp.float32)
        return Tensor(unwrap(self.low) + (unwrap(self.high) - unwrap(self.low)) * u)

    def entropy(self):
        return dispatch(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)

    def log_prob(self, value):
        return dispatch(
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf
            ),
            _t(value), self.low, self.high,
        )


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _t(logits)

    def sample(self, shape=()):
        key = framework.get_rng_key()
        out = jax.random.categorical(
            key, unwrap(self.logits), shape=tuple(shape) + tuple(self.logits.shape[:-1])
        )
        return Tensor(out.astype(jnp.int64))

    def entropy(self):
        return dispatch(
            lambda l: -jnp.sum(
                jax.nn.softmax(l, -1) * jax.nn.log_softmax(l, -1), axis=-1
            ),
            self.logits,
        )

    def log_prob(self, value):
        return dispatch(
            lambda l, v: jnp.take_along_axis(
                jax.nn.log_softmax(l, -1), v.astype(jnp.int32)[..., None], axis=-1
            ).squeeze(-1),
            self.logits, _t(value), nondiff=(1,),
        )

    def probs(self, value):
        from ..ops import exp

        return exp(self.log_prob(value))

    def kl_divergence(self, other):
        return dispatch(
            lambda a, b: jnp.sum(
                jax.nn.softmax(a, -1)
                * (jax.nn.log_softmax(a, -1) - jax.nn.log_softmax(b, -1)),
                axis=-1,
            ),
            self.logits, other.logits,
        )
