"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle-v2.1
capabilities (see SURVEY.md at the repo root for the capability blueprint).

Public surface mirrors `python/paddle/__init__.py` of the reference: tensor
functional API at the top level, plus `nn`, `optimizer`, `amp`, `autograd`,
`jit`, `static`, `io`, `vision`, `metric`, `distributed`, `hapi` (Model).
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

# Multi-controller bootstrap must run before anything touches the XLA
# backend (jax.distributed.initialize's own requirement), so it happens at
# package import when the launcher's FULL env is present — mirroring the
# reference's env-driven trainer identity (PADDLE_TRAINER_ID/...,
# `fleet/launch_utils.py`).  All three variables are required so a
# lingering PADDLE_MASTER alone can't stall an unrelated import waiting on
# peers that will never connect.
if (_os.environ.get("PADDLE_MASTER") or
        _os.environ.get("COORDINATOR_ADDRESS")) and \
        _os.environ.get("PADDLE_TRAINER_ID") is not None and \
        int(_os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
    import warnings as _warnings

    import jax as _jax

    try:
        _jax.distributed.initialize(
            coordinator_address=(_os.environ.get("PADDLE_MASTER")
                                 or _os.environ.get("COORDINATOR_ADDRESS")),
            num_processes=int(_os.environ["PADDLE_TRAINERS_NUM"]),
            process_id=int(_os.environ["PADDLE_TRAINER_ID"]),
        )
    except RuntimeError as _e:  # backend already up / double init
        _warnings.warn(f"paddle_tpu multi-controller bootstrap skipped: {_e}")

from .core import (CPUPlace, CUDAPlace, Place, Tensor, TPUPlace, XPUPlace,
                   bfloat16, bool_, clear_dispatch_cache, complex64,
                   complex128, dispatch_stats, float16, float32, float64,
                   get_default_dtype, get_device, get_flags, int8, int16,
                   int32, int64, is_compiled_with_tpu, seed,
                   set_default_dtype, set_device, set_flags, to_tensor, uint8)
from .ops import *  # noqa: F401,F403 — functional tensor API
from . import ops
from . import autograd
from .autograd import grad, no_grad, enable_grad

# Subsystem imports are kept lazy-tolerant during the staged build; each
# import line activates as the subsystem lands.
from . import nn
from . import optimizer
from . import amp
from . import jit
from . import static
from . import io
from . import metric
from . import vision
from . import distributed
from . import distribution
import importlib as _importlib

# `from .ops import *` above leaks `ops.linalg` under the name `linalg`;
# rebind to the public namespace module (paddle_tpu/linalg.py) explicitly.
linalg = _importlib.import_module(".linalg", __name__)
from . import incubate
from . import inference
from . import quantization
from . import sparsity
from . import text
from . import profiler
from . import observability
from . import regularizer
from .framework.param_attr import ParamAttr
from .framework.io import load, save
from .hapi.model import Model
from . import hapi

# `paddle.disable_static()`/`enable_static()` exist for API compatibility;
# this framework is always imperative-first with jit capture (there is no
# separate static Program interpreter — `paddle_tpu.static` compiles traces).
_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_dynamic_mode():
    return not _static_mode[0]


def is_grad_enabled():
    return autograd.is_grad_enabled()


def set_grad_enabled(mode):
    return autograd.set_grad_enabled(mode)


def device_count():
    import jax

    return len(jax.devices())


def is_tensor(x):
    return isinstance(x, Tensor)


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _flops

    return _flops(net, input_size, custom_ops, print_detail)
